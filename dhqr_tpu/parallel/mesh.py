"""Mesh construction and sharding specs — the worker-pool equivalent.

The reference's execution resources are ``np`` Distributed.jl worker
processes created by ``addprocs(np)`` (reference test/runtests.jl:9) holding
one column block each (``DArray`` distributed ``(1, nworkers())``,
runtests.jl:71). Here the resources are a 1-D ``jax.sharding.Mesh`` over a
``"cols"`` axis; matrices are placed with ``P(None, "cols")`` so rows are
never partitioned — the invariant the reference asserts at src:33.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dhqr_tpu.parallel import topology as _topo

DEFAULT_AXIS = "cols"


def column_mesh(
    n_devices: Optional[int] = None,
    axis_name: str = DEFAULT_AXIS,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """1-D device mesh over the column axis.

    ``n_devices=None`` uses every visible device — the analogue of
    ``addprocs(np)`` sizing the worker pool (runtests.jl:4,9).
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices but only {len(devices)} visible"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis_name,))


def pod_mesh(
    n_devices: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    topo: "tuple[int, int] | str | None" = None,
) -> "tuple[Mesh, _topo.TierAxes]":
    """Two-tier ``("dcn", "ici")`` device mesh + its :class:`TierAxes`
    descriptor (dhqr-pod, round 20).

    ``topo`` is ``(dcn_size, ici_size)`` or a ``"2x4"`` spec string;
    None asks :func:`dhqr_tpu.parallel.topology.detect_topology`
    (``DHQR_TOPO`` env override first, then TPU slice structure). A
    flat device set (no detectable tier, or ``1xP``) still returns a
    valid 1xP pod mesh — the hierarchical schedule degenerates to the
    flat one there, so callers need no special case. Device order is
    preserved: device ``(d, i)`` is flat device ``d * ici_size + i``,
    the same assignment ``column_mesh`` would make.
    """
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"requested {n_devices} devices but only {len(devices)} visible"
            )
        devices = devices[:n_devices]
    if isinstance(topo, str):
        topo = _topo.parse_topo(topo)
    if topo is None:
        topo = _topo.detect_topology(devices) or (1, len(devices))
    dcn, ici = int(topo[0]), int(topo[1])
    if dcn * ici != len(devices):
        raise ValueError(
            f"topology {dcn}x{ici} does not factor the device count "
            f"{len(devices)}"
        )
    mesh = Mesh(np.asarray(devices).reshape(dcn, ici),
                (_topo.DCN_AXIS, _topo.ICI_AXIS))
    return mesh, _topo.TierAxes(dcn_size=dcn, ici_size=ici)


def column_sharding(mesh: Mesh, axis_name=DEFAULT_AXIS) -> NamedSharding:
    """Sharding for an (m, n) matrix: columns split over the mesh, rows whole.

    The reference's ``DArray(..., (1, nworkers()))`` layout (runtests.jl:71)
    with the rows-unpartitioned invariant (src:33) encoded in the spec.
    ``axis_name`` may be a :class:`dhqr_tpu.parallel.topology.TierAxes`
    — columns then shard over both tiers dcn-major (same block order as
    the 1-D mesh over the same device list).
    """
    return NamedSharding(mesh, P(None, _topo.spec_axes(axis_name)))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully-replicated placement — the analogue of the reference's
    ``SharedArray`` side channel for alpha and b (src:302, 318)."""
    return NamedSharding(mesh, P())
