"""Multi-host distribution: meshes spanning hosts over ICI + DCN.

The reference's "cluster" is ``addprocs(np)`` worker processes on one
machine (reference test/runtests.jl:9) — its Distributed.jl backend could
reach real remote workers over TCP, with every reflector broadcast paying a
host round-trip (src:141-143). The TPU framework's multi-host story is the
JAX runtime's: one python process per host, ``jax.distributed.initialize``
to form the global runtime, and a mesh over ``jax.devices()`` (ALL hosts'
devices). The engines in this package need nothing further — ``shard_map``
programs compile once and the runtime routes collectives over ICI within a
slice and DCN across slices.

Guidance for mesh construction (the scaling-relevant choice):

* the column axis carries one psum per panel — O(n/nb) small collectives —
  so it should ride ICI: keep a column mesh within a slice;
* TSQR's single all-gather is DCN-tolerant — its row axis can span hosts
  with negligible cost, which is exactly the regime (m >> n) where
  multi-host capacity matters most.

Usage (same script on every host):

    from dhqr_tpu.parallel.multihost import initialize, global_column_mesh
    initialize(coordinator_address="10.0.0.1:1234",
               num_processes=4, process_id=HOST_ID)
    mesh = global_column_mesh()
    x = dhqr_tpu.lstsq(A, b, mesh=mesh)
"""

from __future__ import annotations

from typing import Optional

import jax

from dhqr_tpu.parallel.mesh import DEFAULT_AXIS, column_mesh
from dhqr_tpu.parallel.sharded_tsqr import ROW_AXIS, row_mesh


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    **kwargs,
) -> None:
    """Join the global JAX runtime (no-op when already initialized).

    Thin wrapper over ``jax.distributed.initialize`` so framework users have
    one import surface; on managed TPU pods all arguments are discovered
    from the environment and may be omitted. Outside a managed environment,
    calling with no arguments is a single-process no-op (the same script
    then runs standalone — the reference's np=1 degenerate mode).
    """
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **kwargs,
        )
    except RuntimeError as e:
        msg = str(e).lower()
        if "already initialized" in msg:
            return
        if ("before any jax computations" in msg
                and coordinator_address is None and num_processes is None
                and process_id is None and not kwargs):
            # Backend already live in single-process mode and nothing
            # multi-process was requested: the documented no-op (an
            # explicit coordinator request after backend init still
            # surfaces — that one IS a real ordering bug).
            return
        raise
    except ValueError:
        if (coordinator_address is not None or num_processes is not None
                or process_id is not None or kwargs):
            raise  # explicit multi-process request that failed — surface it
        # no coordinator anywhere and nothing requested: single-process mode


def global_column_mesh(axis_name: str = DEFAULT_AXIS):
    """Column mesh over every device of every host (ICI+DCN collectives)."""
    return column_mesh(axis_name=axis_name, devices=jax.devices())


def global_pod_mesh(topo=None):
    """Two-tier ``("dcn", "ici")`` mesh over every device of every host
    + its ``TierAxes`` descriptor (dhqr-pod, round 20).

    The pod-scale replacement for :func:`global_column_mesh`: the DCN
    tier is discovered from the multi-slice runtime (``slice_index``,
    falling back to per-process grouping) or forced with
    ``DHQR_TOPO=PdcnxPici`` / the ``topo`` argument, and the sharded
    engines run the hierarchical reduce-inside-ICI-first schedule on
    it (parallel/wire.py). On a single slice this degenerates to a
    1xP mesh — same collectives as the flat tier.
    """
    from dhqr_tpu.parallel.mesh import pod_mesh

    return pod_mesh(devices=jax.devices(), topo=topo)


def global_row_mesh(axis_name: str = ROW_AXIS):
    """Row mesh over every device of every host — the TSQR axis, whose one
    all-gather tolerates DCN latency."""
    return row_mesh(axis_name=axis_name, devices=jax.devices())


def process_info() -> dict:
    """Topology summary for logs — the analogue of the reference printing
    its worker/thread layout at startup (runtests.jl:10, 28)."""
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
        "backend": jax.default_backend(),
    }
