"""Mesh-sharded least-squares solve (SURVEY.md §7 stage 4).

TPU-native replacement for the reference's distributed solve
(reference src/DistributedHouseholderQR.jl:226-282):

* Stage 1 (apply Q^H): the reference walks workers *sequentially in pid
  order* — column order is dependency order — mutating b through shared
  memory (src:226-242). Here each nb-wide panel's reflectors are broadcast
  with one psum and the panel transform is applied replicated, so the
  sequential chain is panels, not workers, and lives inside one program.
* Stage 2 (back-substitution): the reference runs n rounds of
  scalar partial-row-dot futures, gathered on the master (src:256-282) —
  the latency-bound tail. Here panels are solved right-to-left: the owner
  back-substitutes its nb x nb diagonal block and computes its columns'
  contribution to the remaining rows; one psum per panel broadcasts both
  (n/nb collectives of O(n) words instead of n rounds of host RPCs).

b stays replicated throughout — the analogue of the reference's
``SharedArray(b)`` (src:318).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dhqr_tpu.utils.compat import shard_map

# dhqr-pulse (round 16) runtime comms seam — acyclic, one None check
# disarmed (see parallel/sharded_qr.py).
from dhqr_tpu.obs import pulse as _pulse

# dhqr-wire (round 18) compression seam — every collective below
# routes through it (DHQR009); comms=None is a verbatim passthrough.
from dhqr_tpu.parallel import wire as _wire

# dhqr-armor (round 19) ABFT verification seam (DHQR010).
from dhqr_tpu import armor as _armor

from dhqr_tpu.ops.blocked import (
    MAX_UNROLLED_PANELS,
    _panels_schedule,
    apply_block_reflector_h,
    shifted_tril,
)
from dhqr_tpu.ops.householder import DEFAULT_PRECISION
from dhqr_tpu.parallel.mesh import DEFAULT_AXIS, column_sharding, replicated_sharding

# dhqr-pod (round 20): two-tier topology descriptor + axis helpers
# (plain string axes take the exact pre-pod paths).
from dhqr_tpu.parallel import topology as _topo


def _apply_qt_shard_body(
    Hl, b, *, n: int, nb: int, axis: str,
    precision: str = DEFAULT_PRECISION, layout: str = "block",
    comms: "str | None" = None,
):
    """b <- Q^H b, panel by panel; Hl is the local (m, nloc) block.

    Per panel, the owner's reflectors are broadcast with one psum — the
    equivalent of stage 1's per-worker visit (src:227-229). Many panels run
    as scans inside <= MAX_UNROLLED_PANELS statically row-sliced
    super-blocks (bounded program size; the row shrinkage bounds the psum'd
    panel to the super-block's rows, and structural zeros above the
    reflector row make the within-block unsliced update exact).
    """
    from dhqr_tpu.parallel.sharded_qr import _panel_owner, _panel_owner_traced

    m, nloc = Hl.shape
    nproc = n // nloc
    p = _topo.axis_index(axis)
    vec = b.ndim == 1
    B = b[:, None] if vec else b
    num_panels = n // nb  # nb | nloc | n in the sharded path (checked)

    if num_panels <= MAX_UNROLLED_PANELS:
        for k in range(0, n, nb):
            bsz = min(nb, n - k)
            owner, kl = _panel_owner(k, n, nloc, nb, layout)
            mine = p == owner
            panel = jnp.tril(lax.slice(Hl, (k, kl), (m, kl + bsz)))
            panel = _wire.wire_psum(
                jnp.where(mine, panel, jnp.zeros_like(panel)), axis, comms)
            tail = lax.slice(B, (k, 0), B.shape)
            B = B.at[k:, :].set(apply_block_reflector_h(panel, tail, precision))
        return B[:, 0] if vec else B

    # Super-block row shrinkage (same scheme as the factor engines): panel
    # kb's reflectors live in rows k:m, so the psum'd panel and the updated
    # B rows can be statically cut to the super-block's row range — without
    # it every panel would move a full m x nb block over the mesh (m*n
    # words total, as much as the matrix itself).
    _, _, ppo = _panels_schedule(n, nb)  # rem is 0 on the sharded path
    for ob in range(0, num_panels, ppo):
        pcount = min(ppo, num_panels - ob)
        K = ob * nb
        ms = m - K
        Bs = lax.slice(B, (K, 0), B.shape)  # rows K:

        def body(Bs, q, ob=ob, ms=ms, K=K):
            kb = ob + q
            c = kb * nb - K  # reflector start row within the super-block
            owner, kl = _panel_owner_traced(kb, nproc, nloc, nb, layout)
            mine = p == owner
            Y = shifted_tril(
                lax.dynamic_slice(Hl, (jnp.int32(K), kl), (ms, nb)), c
            )
            Y = _wire.wire_psum(jnp.where(mine, Y, jnp.zeros_like(Y)),
                                axis, comms)
            # Y is zero above row c, so only rows c: of Bs change.
            return apply_block_reflector_h(Y, Bs, precision), None

        Bs, _ = lax.scan(body, Bs, jnp.arange(pcount, dtype=jnp.int32))
        B = B.at[K:, :].set(Bs)
    return B[:, 0] if vec else B


def _backsub_shard_body(
    Hl, alpha, c, *, n: int, nb: int, axis: str,
    precision: str = DEFAULT_PRECISION, layout: str = "block",
    comms: "str | None" = None,
):
    """Solve R x = c[:n]; R packed in (Hl strict upper, alpha). Returns x.

    Right-to-left panel sweep replacing the reference's n fetch rounds
    (src:256-282). Per panel, the owner solves the diagonal block and forms
    its columns' update to all earlier rows; both ride one psum. ``c`` may
    be (m,) or (m, k).
    """
    from dhqr_tpu.parallel.sharded_qr import _panel_owner, _panel_owner_traced

    m, nloc = Hl.shape
    nproc = n // nloc
    p = _topo.axis_index(axis)
    rows_n = lax.iota(jnp.int32, n)[:, None]
    vec = c.ndim == 1
    C = (c[:, None] if vec else c)[:n]
    x = jnp.zeros_like(C)
    num_panels = n // nb  # nb | nloc | n in the sharded path (checked)

    if num_panels <= MAX_UNROLLED_PANELS:
        for k in reversed(range(0, n, nb)):
            bsz = min(nb, n - k)
            owner, kl = _panel_owner(k, n, nloc, nb, layout)
            mine = p == owner
            # Owner's diagonal block: strict upper from H, diagonal from
            # alpha (the reference's R packing, src:244-254).
            blk = lax.slice(Hl, (k, kl), (k + bsz, kl + bsz))
            Rpp = jnp.triu(blk, k=1) + jnp.diag(
                lax.dynamic_slice_in_dim(alpha, k, bsz)
            )
            xp = lax.linalg.triangular_solve(
                Rpp, C[k : k + bsz], left_side=True, lower=False
            )  # (bsz, nrhs)
            # Owner's columns' contribution to earlier rows: R[0:k, panel]@xp.
            above = (
                lax.slice(Hl, (0, kl), (k, kl + bsz))
                if k
                else jnp.zeros((0, bsz), Hl.dtype)
            )
            delta = jnp.matmul(above, xp, precision=precision)  # (k, nrhs)
            packed = jnp.concatenate(
                [delta, xp, jnp.zeros((n - k - bsz, xp.shape[1]), C.dtype)]
            )
            packed = _wire.wire_psum(
                jnp.where(mine, packed, jnp.zeros_like(packed)), axis, comms)
            x = jnp.where((rows_n >= k) & (rows_n < k + bsz), packed, x)
            C = jnp.where(rows_n < k, C - packed, C)
        return x[:, 0] if vec else x

    # Right-to-left super-blocks with static row shrinkage: every panel in
    # super-block ob touches only rows < Ke = (ob+pcount)*nb, so the packed
    # psum per panel is Ke x nrhs instead of n x nrhs — halving the
    # back-sub's collective traffic on average.
    _, _, ppo = _panels_schedule(n, nb)  # rem is 0 on the sharded path
    for ob in reversed(range(0, num_panels, ppo)):
        pcount = min(ppo, num_panels - ob)
        Ke = (ob + pcount) * nb
        rows_e = lax.iota(jnp.int32, Ke)[:, None]
        xs = lax.slice(x, (0, 0), (Ke, x.shape[1]))
        Cs = lax.slice(C, (0, 0), (Ke, C.shape[1]))

        def body(carry, kb, Ke=Ke, rows_e=rows_e):
            xs, Cs = carry
            k = kb * nb
            owner, kl = _panel_owner_traced(kb, nproc, nloc, nb, layout)
            mine = p == owner
            # Owner's column strip, rows < Ke only (R rows for this block).
            strip = lax.dynamic_slice(Hl, (jnp.int32(0), kl), (Ke, nb))
            blk = lax.dynamic_slice(strip, (k, jnp.int32(0)), (nb, nb))
            Rpp = jnp.triu(blk, k=1) + jnp.diag(
                lax.dynamic_slice_in_dim(alpha, k, nb)
            )
            Ck = lax.dynamic_slice(Cs, (k, jnp.int32(0)), (nb, Cs.shape[1]))
            xp = lax.linalg.triangular_solve(Rpp, Ck, left_side=True, lower=False)
            # R[0:k, panel] @ xp with the strip masked to rows < k (rows in
            # [k, k+nb) are the diagonal block already solved; rows beyond
            # hold reflector entries, not R).
            above = jnp.where(rows_e < k, strip, jnp.zeros_like(strip))
            delta = jnp.matmul(above, xp, precision=precision)  # (Ke, nrhs)
            packed = lax.dynamic_update_slice(delta, xp, (k, jnp.int32(0)))
            packed = _wire.wire_psum(
                jnp.where(mine, packed, jnp.zeros_like(packed)), axis, comms
            )
            xs = jnp.where((rows_e >= k) & (rows_e < k + nb), packed, xs)
            Cs = jnp.where(rows_e < k, Cs - packed, Cs)
            return (xs, Cs), None

        (xs, Cs), _ = lax.scan(
            body, (xs, Cs),
            jnp.arange(ob + pcount - 1, ob - 1, -1, dtype=jnp.int32),
        )
        x = x.at[:Ke].set(xs)
        C = C.at[:Ke].set(Cs)
    return x[:, 0] if vec else x


@lru_cache(maxsize=None)
def _build_solve(
    mesh: Mesh, axis_name: str, n: int, nb: int, precision: str, layout: str,
    comms: "str | None" = None, seam=None,
):
    # ``seam``: round-19 cache-key material only (wire.seam_token).
    def full(Hl, alpha, b):
        cb = _apply_qt_shard_body(
            Hl, b, n=n, nb=nb, axis=axis_name, precision=precision,
            layout=layout, comms=comms,
        )
        return _backsub_shard_body(
            Hl, alpha, cb,
            n=n, nb=nb, axis=axis_name, precision=precision, layout=layout,
            comms=comms,
        )

    return jax.jit(
        shard_map(
            full,
            mesh=mesh,
            in_specs=(P(None, _topo.spec_axes(axis_name)), P(), P()),
            out_specs=P(),
            check_vma=False,
        )
    )


def sharded_solve(
    H: jax.Array,
    alpha: jax.Array,
    b: jax.Array,
    mesh: Mesh,
    block_size: int = 128,
    axis_name: str = DEFAULT_AXIS,
    precision: str = DEFAULT_PRECISION,
    layout: str = "block",
    _H_in_store_layout: bool = False,
    comms: "str | None" = None,
) -> jax.Array:
    """x = argmin ||A x - b|| from the sharded packed factorization.

    The reference's ``solve_householder!`` orchestration (src:284-294) as one
    compiled program: Q^H apply then panel back-substitution, b replicated.
    ``H`` is taken in natural column order unless ``_H_in_store_layout`` says
    it already sits in the layout's storage order (the ``sharded_lstsq``
    fast path); x is always returned in natural order.
    """
    from dhqr_tpu.parallel.layout import plan_padding
    from dhqr_tpu.parallel.sharded_qr import (
        _check_divisibility,
        _to_store_layout,
    )

    comms = _wire.resolve_comms(comms)
    m, n = H.shape
    axis_name = _topo.resolve_axis(mesh, axis_name)
    nproc = _topo.axis_size(mesh, axis_name)
    ptag = _topo.axis_label(axis_name, nproc)
    nb, n_pad = plan_padding(n, nproc, block_size)
    if n_pad != n:
        # Arbitrary n: pad H with zero columns (v = 0 is the identity
        # reflector under the compact-WY unit-diagonal solve) and alpha with
        # ones (unit R diagonal). The padded R has zero coupling into the
        # leading rows, so x[:n] is exact; zero rows are appended if the
        # padded width exceeds m (reflectors and R ignore zero rows).
        if _H_in_store_layout:
            raise ValueError(
                f"internal store-layout chaining requires n divisible by "
                f"nb*P = {nb * nproc}, got n={n}: pad the input before chaining"
            )
        k = n_pad - n
        H = jnp.concatenate([H, jnp.zeros((m, k), H.dtype)], axis=1)
        alpha = jnp.concatenate([alpha, jnp.ones((k,), alpha.dtype)])
        if m < n_pad:
            H = jnp.concatenate(
                [H, jnp.zeros((n_pad - m, n_pad), H.dtype)], axis=0
            )
            pad_b = [(0, n_pad - m)] + [(0, 0)] * (b.ndim - 1)
            b = jnp.pad(b, pad_b)
        x = sharded_solve(
            H, alpha, b, mesh, block_size=nb, axis_name=axis_name,
            precision=precision, layout=layout, comms=comms,
        )
        return x[:n]
    _check_divisibility(m, n, nproc, nb, layout)
    base_label = f"sharded_solve[P={ptag},{m}x{n},nb={nb},{layout}]"
    comms = _armor.effective_comms(base_label, comms)
    if not _H_in_store_layout:
        H = _to_store_layout(H, n, nproc, nb, layout)
    H = jax.device_put(H, column_sharding(mesh, axis_name))
    alpha = jax.device_put(alpha, replicated_sharding(mesh))
    b = jax.device_put(b, replicated_sharding(mesh))

    def _dispatch(wire_comms):
        fn = _build_solve(mesh, axis_name, n, nb, precision, layout,
                          wire_comms, _wire.seam_token(wire_comms))
        if _pulse.active() is None:
            return fn(H, alpha, b)
        return _pulse.observed_dispatch(
            f"sharded_solve[P={ptag},{m}x{n},nb={nb},{layout}"
            + (f",w{wire_comms}" if wire_comms else "") + "]",
            lambda: fn(H, alpha, b),
            abstract=lambda: jax.make_jaxpr(fn)(H, alpha, b),
            n_devices=nproc, wire_format=wire_comms)

    if _armor.active() is None or _H_in_store_layout:
        # Internal chaining (sharded_lstsq) verifies the whole
        # factor+solve pipeline once, at the top level, against A.
        return _dispatch(comms)
    # Standalone solve: handed factors, not A, so the checkable
    # invariant is finiteness only (NaN-loud wire-tag poisoning and
    # injected NaN are caught; docs/DESIGN.md "Fault tolerance for the
    # sharded tier" documents the coverage split).
    return _armor.checked_dispatch(
        base_label, lambda: _dispatch(comms),
        lambda x: (_armor.checks.finite_gap(x), None),
        engine="householder", comms=comms,
        degrade=(lambda: _dispatch(None)) if comms else None,
        plan_shape=("lstsq", m, n, str(H.dtype), nproc))


def sharded_lstsq(
    A: jax.Array,
    b: jax.Array,
    mesh: Mesh,
    block_size: int = 128,
    axis_name: str = DEFAULT_AXIS,
    precision: str = DEFAULT_PRECISION,
    layout: str = "block",
    norm: str = "accurate",
    use_pallas: str = "auto",
    panel_impl: str = "loop",
    trailing_precision: "str | None" = None,
    lookahead: bool = False,
    agg_panels: "int | None" = None,
    overlap_depth: "int | None" = None,
    apply_precision: "str | None" = None,
    comms: "str | None" = None,
    policy=None,
) -> jax.Array:
    """One-shot distributed least squares: factor + solve on the mesh.

    The distributed equivalent of ``qr!(A) \\ b`` (reference runtests.jl:77-78).
    With ``layout="cyclic"`` the factorization stays in storage order between
    the factor and solve stages — no cross-device column permute in between.
    Arbitrary n is padded ONCE here (the orthogonal extension, see
    ``sharded_qr._pad_cols_orthogonal``) so the store-layout chaining between
    the stages stays intact; x is sliced back to n.

    ``apply_precision`` (default: ``precision``) sets the solve stage's
    matmul precision — the Q^H apply and back-substitution GEMMs.
    ``policy`` sets the whole precision tuple at once (panel -> factor
    ``precision``, trailing -> ``trailing_precision``, apply -> this
    knob). ``policy.refine`` must be 0 here: this function returns x
    straight from one factor+solve pass, so a refining policy's defining
    accuracy-recovery step would be silently skipped — mesh-path
    refinement lives in ``models.qr_model`` (``lstsq(..., mesh=,
    policy=...)``), which reuses this pipeline's factorization via
    ``qr()``.
    """
    from dhqr_tpu.parallel.layout import plan_padding
    from dhqr_tpu.parallel.sharded_qr import (
        _pad_cols_orthogonal,
        sharded_blocked_qr,
    )
    from dhqr_tpu.precision import (apply_policy_to_comms_arg,
                                    apply_policy_to_factor_args,
                                    resolve_policy)

    comms = apply_policy_to_comms_arg(policy, comms)
    if policy is not None:
        if apply_precision is not None:
            raise ValueError(
                "pass either policy= or apply_precision=, not both")
        pol = resolve_policy(policy)
        if pol.refine:
            raise ValueError(
                "policy.refine > 0 is not supported by sharded_lstsq "
                "(one factor+solve pass; the refinement would be "
                "silently skipped) — use models.qr_model.lstsq(..., "
                "mesh=, policy=...), which loops the sharded solve, or "
                "a refine=0 policy"
            )
        apply_precision = pol.resolved_apply()
    precision, trailing_precision = apply_policy_to_factor_args(
        policy, precision, trailing_precision,
        default_precision=DEFAULT_PRECISION)
    if apply_precision is None:
        apply_precision = precision
    m, n = A.shape
    m0, n0 = m, n   # the CALLER's shape — the tune/demotion plan key
    axis_name = _topo.resolve_axis(mesh, axis_name)
    nproc = _topo.axis_size(mesh, axis_name)
    ptag = _topo.axis_label(axis_name, nproc)
    nb, n_pad = plan_padding(n, nproc, block_size)
    if n_pad != n:
        A = _pad_cols_orthogonal(A, n_pad)
        pad_b = [(0, n_pad - n)] + [(0, 0)] * (b.ndim - 1)
        b = jnp.pad(b, pad_b)  # zero rows for the appended identity rows

    def _dispatch(wire_comms):
        H, alpha = sharded_blocked_qr(
            A, mesh, block_size=nb, axis_name=axis_name,
            precision=precision, layout=layout,
            _store_layout_output=True, norm=norm, use_pallas=use_pallas,
            panel_impl=panel_impl,
            trailing_precision=trailing_precision, lookahead=lookahead,
            agg_panels=agg_panels, overlap_depth=overlap_depth,
            comms=wire_comms,
        )
        return sharded_solve(
            H, alpha, b, mesh,
            block_size=nb, axis_name=axis_name, precision=apply_precision,
            layout=layout, _H_in_store_layout=True, comms=wire_comms,
        )

    if _armor.active() is None:
        return _dispatch(comms)[:n]
    # ABFT verification at the top of the pipeline (round 19): the
    # chained factor/solve stages skip their own armor wrap
    # (_store_layout_output/_H_in_store_layout), so one O(mn)
    # normal-equations checksum covers the whole factor+solve and a
    # recovery re-dispatch re-runs BOTH stages.
    base_label = (f"sharded_lstsq[P={ptag},{m}x{A.shape[1]},nb={nb},"
                  f"{layout}]")
    comms_eff = _armor.effective_comms(base_label, comms)
    # plan_shape carries the CALLER's (m, n): tune.resolve_plan keys
    # demotion on the shape the caller asked for, and the padded twin
    # would never match it.
    x = _armor.checked_dispatch(
        base_label, lambda: _dispatch(comms_eff),
        lambda xx: (_armor.checks.lstsq_gap(A, b, xx), None),
        engine="householder", comms=comms_eff,
        degrade=(lambda: _dispatch(None)) if comms_eff else None,
        plan_shape=("lstsq", m0, n0, str(A.dtype), nproc))
    return x[:n]


# Comms contract (dhqr-audit): psum only — one shrinking (m-k, nb)
# panel psum per apply panel plus one packed (n, nrhs) psum per
# back-substitution panel (analysis/cost_model.py `sharded_solve`);
# compressed: the same psums at the wire itemsize
# (sharded_solve_wire_bf16, round 18).
