"""Row-sharded CholeskyQR2 least squares — one psum per pass.

The distributed form of :mod:`dhqr_tpu.ops.cholqr`: rows sharded over the
TSQR axis; each Gram matrix is a local syrk plus ONE ``psum`` of an n x n
block, the Cholesky + triangular work runs replicated (tiny), and the
Q-updates stay local. Three psums total (one per Gram pass plus one for
Q^H b; four in the shifted three-pass form) of O(n^2) words per device
regardless of m — the communication-optimal regime for m >> n, every
local flop a GEMM on the MXU (see ops/cholqr.py for the conditioning
window; this is the pod-scale recipe of arxiv 2112.09017).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dhqr_tpu.utils.compat import shard_map

# dhqr-pulse (round 16) runtime comms seam — acyclic, one None check
# disarmed (see parallel/sharded_qr.py).
from dhqr_tpu.obs import pulse as _pulse

# dhqr-wire (round 18) compression seam (DHQR009). The Gram psums are
# DENSE reductions (every device contributes), so the bf16 rung adds
# in bf16 at ring depth <= P-1 — same order as the quantization error
# at P <= 8 — and the int8 rung degrades to bf16 at the seam
# (per-device scales cannot ride an additive reduction).
from dhqr_tpu.parallel import wire as _wire

# dhqr-armor (round 19) ABFT verification seam (DHQR010).
from dhqr_tpu import armor as _armor

# dhqr-pod (round 20): two-tier topology descriptor + axis helpers.
from dhqr_tpu.parallel import topology as _topo

from dhqr_tpu.ops.cholqr import _cholqr_passes
from dhqr_tpu.ops.solve import as_matrix_rhs
from dhqr_tpu.ops.householder import DEFAULT_PRECISION
from dhqr_tpu.parallel.sharded_tsqr import ROW_AXIS


def _cholqr_shard_body(Al, bl, *, axis: str, precision: str, shift: bool,
                       comms: "str | None" = None):
    """Per-device rows of A; returns x replicated.

    Pass structure is :func:`dhqr_tpu.ops.cholqr._cholqr_passes` — shared
    with the single-device engine — with the Gram matrix reduced by one
    psum per pass (replicated, so the Cholesky is deterministic everywhere).
    """
    gram = lambda X: _wire.wire_psum(
        jnp.matmul(jnp.conj(X.T), X, precision=precision), axis, comms,
        onehot=False,
    )
    Ql, R = _cholqr_passes(Al, gram, precision, shift)
    Bl, restore = as_matrix_rhs(bl)
    C = _wire.wire_psum(
        jnp.matmul(jnp.conj(Ql.T), Bl, precision=precision), axis, comms,
        onehot=False)
    x = lax.linalg.triangular_solve(R, C, left_side=True, lower=False)
    if comms is not None:
        # Compressed Gram psums round R to ~wire eps, which the raw
        # solve cannot buy back — run CSNE_SWEEPS corrected-semi-normal
        # sweeps against the true local rows (residual matvec exact in
        # f32; the (n, nrhs) correction reduction rides the compressed
        # wire as a second-order term — cost_model.cholqr_lstsq_wire).
        def sns(g):
            y = lax.linalg.triangular_solve(
                R, g, left_side=True, lower=False, transpose_a=True,
                conjugate_a=True)
            return lax.linalg.triangular_solve(R, y, left_side=True,
                                               lower=False)

        for _ in range(_wire.CSNE_SWEEPS):
            r_loc = Bl - jnp.matmul(Al, x, precision="highest")
            # The (n, nrhs) correction reduction stays on the F32 wire
            # (comms=None is the seam's exact passthrough): quantizing
            # it would cap the sweep's contraction at the wire eps it
            # exists to remove; its volume is O(1/n) of the Gram psums
            # (priced by cost_model.cholqr_lstsq_wire).
            g = _wire.wire_psum(
                jnp.matmul(jnp.conj(Al.T), r_loc, precision="highest"),
                axis, None, onehot=False)
            x = x + sns(g)
    return restore(x)


@lru_cache(maxsize=None)
def _build_cholqr(mesh: Mesh, axis_name: str, precision: str, shift: bool,
                  comms: "str | None" = None, seam=None):
    # ``seam``: round-19 cache-key material only (wire.seam_token).
    body = partial(
        _cholqr_shard_body, axis=axis_name, precision=precision, shift=shift,
        comms=comms,
    )
    spec = _topo.spec_axes(axis_name)
    return jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P(spec, None), P(spec)),
            out_specs=P(),
            check_vma=False,  # x is replicated by construction (psum inputs)
        )
    )


def sharded_cholqr_lstsq(
    A: jax.Array,
    b: jax.Array,
    mesh: Mesh,
    axis_name: str = ROW_AXIS,
    precision: str = DEFAULT_PRECISION,
    shift: bool = False,
    comms: "str | None" = None,
) -> jax.Array:
    """Distributed least squares via CholeskyQR2: rows sharded, three psums
    (four with ``shift=True``, the shifted-CholeskyQR3 wide-window form).

    Requires m divisible by the mesh size. Returns x replicated. Same
    conditioning window as :func:`dhqr_tpu.ops.cholqr.cholesky_qr2` —
    prefer :func:`sharded_tsqr_lstsq` for ill-conditioned problems.
    """
    comms = _wire.resolve_comms(comms)
    m, n = A.shape
    if m < n:
        raise ValueError(f"lstsq requires m >= n, got {A.shape}")
    axis_name = _topo.resolve_axis(mesh, axis_name)
    nproc = _topo.axis_size(mesh, axis_name)
    ptag = _topo.axis_label(axis_name, nproc)
    if m % nproc != 0:
        raise ValueError(f"m={m} must be divisible by mesh size {nproc}")
    spec = _topo.spec_axes(axis_name)
    A = jax.device_put(A, NamedSharding(mesh, P(spec, None)))
    b = jax.device_put(b, NamedSharding(mesh, P(spec)))
    base_label = (f"cholqr_lstsq[P={ptag},{m}x{n}"
                  + (",shift" if shift else "") + "]")
    comms = _armor.effective_comms(base_label, comms)

    def _dispatch(wire_comms):
        fn = _build_cholqr(mesh, axis_name, precision, bool(shift),
                           wire_comms, _wire.seam_token(wire_comms))
        if _pulse.active() is None:
            return fn(A, b)
        return _pulse.observed_dispatch(
            f"cholqr_lstsq[P={ptag},{m}x{n}" + (",shift" if shift else "")
            + (f",w{wire_comms}" if wire_comms else "") + "]",
            lambda: fn(A, b), abstract=lambda: jax.make_jaxpr(fn)(A, b),
            n_devices=nproc, wire_format=wire_comms)

    if _armor.active() is None:
        return _dispatch(comms)
    # ABFT verification (round 19): O(mn) normal-equations checksum ->
    # re-dispatch -> degrade wire -> typed (dhqr_tpu.armor).
    return _armor.checked_dispatch(
        base_label, lambda: _dispatch(comms),
        lambda x: (_armor.checks.lstsq_gap(A, b, x), None),
        engine="cholqr3" if shift else "cholqr2", comms=comms,
        degrade=(lambda: _dispatch(None)) if comms else None,
        plan_shape=("lstsq", m, n, str(A.dtype), nproc))


# Comms contract (dhqr-audit): psum only, 2*n^2 + n*nrhs words per
# solve (analysis/cost_model.py `cholqr_lstsq`) — the m-independence IS
# the engine's value, so a volume regression here is a DHQR302 finding.
# The COMPRESSED variant adds CSNE_SWEEPS correction psums and halves
# the wire bytes (round 18 — `cholqr_lstsq_wire` model).
