"""Mesh-sharded factorization engines (SURVEY.md §7 stage 2).

TPU-native re-design of the reference's distributed tier
(reference src/DistributedHouseholderQR.jl:115-213). The reference runs the
panel loop by *migrating* control across worker processes — per column it
serializes the m-element reflector to every worker over TCP and blocks on
``@sync``/``fetch`` (src:141-143, flagged "this is most expensive"). Here the
whole factorization is ONE compiled SPMD program over a 1-D column mesh:

* the owner's column/panel is broadcast with a single ``psum`` over ICI
  (devices contribute zeros except the owner — an all-reduce *is* the
  broadcast, and XLA lowers it to the fastest collective for the topology);
* the reflector math is computed redundantly-replicated on every device
  (cheaper than a second collective);
* the trailing update touches only local columns, masked by global index —
  the moral equivalent of ``jjs = intersect(j+1:n, colrange)`` (src:201).

Two engines, mirroring the single-device pair:
``sharded_householder_qr`` (unblocked, one psum per column) and
``sharded_blocked_qr`` (compact-WY, one psum per nb-wide panel, trailing
update as local GEMMs on the MXU).

Constraints (documented, checked): n divisible by the mesh size;
for the blocked engine the panel width must divide the local block width so
every panel has a single owner (the reference's panels equal whole worker
blocks, src:115-120 — ours are finer).
"""

from __future__ import annotations

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dhqr_tpu.utils.compat import shard_map

# dhqr-pulse (round 16): the runtime collective-profiling seam. The
# import is acyclic (obs only reads utils/*; its providers import
# their subjects lazily) and the disarmed cost at each dispatch below
# is one module-global None check — the faults/obs discipline.
from dhqr_tpu.obs import pulse as _pulse

# dhqr-wire (round 18): EVERY collective below routes through the
# compression seam — comms=None is a verbatim lax passthrough, so the
# accurate tier stays bit-identical by construction; dhqr-lint DHQR009
# rejects raw lax collectives in this package.
from dhqr_tpu.parallel import wire as _wire

# dhqr-armor (round 19): the ABFT verification seam (DHQR010) — the
# public entry points below wrap their dispatch in
# armor.checked_dispatch when armed (weighted-checksum invariant,
# recovery ladder, typed refusal); disarmed cost is one module-global
# None check and the build-cache keys stay byte-identical.
from dhqr_tpu import armor as _armor

from dhqr_tpu.ops.blocked import (
    MAX_UNROLLED_PANELS,
    _factor_group,
    _panels_schedule,
    apply_block_reflector_h,
    shifted_tril,
)
from dhqr_tpu.ops.householder import (
    DEFAULT_PRECISION,
    _householder_qr_impl,
    _panel_qr_masked,
    householder_reflector,
)
from dhqr_tpu.parallel.mesh import DEFAULT_AXIS, column_sharding

# dhqr-pod (round 20): the two-tier topology descriptor + the four
# axis helpers that keep this engine tier-agnostic (a plain string
# axis takes the exact pre-pod paths — same labels, same cache keys).
from dhqr_tpu.parallel import topology as _topo


def _local_gidx(p, n: int, nloc: int, nb: int, layout: str):
    """Global (natural) column index of each local column — the traced
    generalization of ``LocalColumnBlock``'s Δj offset arithmetic (src:34).

    "block": device p holds the contiguous columns [p*nloc, (p+1)*nloc).
    "cyclic": device p holds nb-wide column blocks {kb : kb % P == p},
    stored consecutively (the layout :func:`cyclic_store_columns` produces).
    """
    P = n // nloc
    c = lax.iota(jnp.int32, nloc)
    if layout == "block":
        return p * nloc + c
    if layout == "cyclic":
        return ((c // nb) * P + p) * nb + c % nb
    raise ValueError(f"layout must be 'block' or 'cyclic', got {layout!r}")


def _panel_owner(k: int, n: int, nloc: int, nb: int, layout: str):
    """(owner device, local column offset) of the nb-wide panel at column k.

    Static Python ints — panel offsets are unrolled, so placement is free.
    """
    P = n // nloc
    if layout == "block":
        owner = k // nloc
        return owner, k - owner * nloc
    kb = k // nb
    return kb % P, (kb // P) * nb


def _col_owner(col: int, n: int, nproc: int, nb: int, layout: str) -> int:
    """Owner device of global column ``col`` — the armor seam's
    checksum-gap localization (worst discrepant column -> implicated
    shard; :class:`dhqr_tpu.armor.ShardFailure` carries it)."""
    nloc = n // nproc
    if layout == "cyclic":
        return (int(col) // max(nb, 1)) % nproc
    return int(col) // nloc


def _panel_owner_traced(kb, P: int, nloc: int, nb: int, layout: str):
    """Traced twin of :func:`_panel_owner` for scanned panel loops.

    ``kb`` is the (traced) panel index; returns traced (owner, local col
    offset) — the same arithmetic with only static divisors.
    """
    if layout == "block":
        k = kb * nb
        owner = k // nloc
        return owner, k - owner * nloc
    if layout == "cyclic":
        return kb % P, (kb // P) * nb
    raise ValueError(f"layout must be 'block' or 'cyclic', got {layout!r}")


def _unblocked_shard_body(
    Al, *, n: int, axis: str,
    precision: str = DEFAULT_PRECISION, layout: str = "block", store_nb: int = 1,
    norm: str = "accurate", comms: "str | None" = None,
):
    """Per-device body: Al is the local (m, nloc) column block.

    ``store_nb`` is the cyclic store's block width — 1 by default, but set
    to the *solve* panel width when the factorization feeds straight into
    ``sharded_solve`` so both stages share one storage order.
    """
    m, nloc = Al.shape
    p = _topo.axis_index(axis)
    P = n // nloc
    delta_j = p * nloc  # global column offset — LocalColumnBlock.Δj (src:34)
    rows = lax.iota(jnp.int32, m)
    gidx = _local_gidx(p, n, nloc, store_nb, layout)  # natural idx of local cols

    def step(j, carry):
        Al, alpha = carry
        if layout == "cyclic":
            kb = j // store_nb  # owning block, round-robin over devices
            jl = (kb // P) * store_nb + j % store_nb
            mine = (kb % P) == p
        else:
            jl = jnp.clip(j - delta_j, 0, nloc - 1)
            mine = (j >= delta_j) & (j < delta_j + nloc)
        col_local = lax.dynamic_slice_in_dim(Al, jl, 1, axis=1)[:, 0]
        # Broadcast = all-reduce of a one-hot contribution (reference's
        # per-column Hj serialization to every worker, src:138-143),
        # over the comms wire format (exact accumulation: zeros).
        col = _wire.wire_psum(
            jnp.where(mine, col_local, jnp.zeros_like(col_local)), axis,
            comms)
        v, alpha_j = householder_reflector(col, j, norm)
        newcol = jnp.where(rows >= j, v, col)
        Al_upd = lax.dynamic_update_slice_in_dim(Al, newcol[:, None], jl, axis=1)
        Al = jnp.where(mine, Al_upd, Al)
        alpha = lax.dynamic_update_slice_in_dim(alpha, alpha_j[None], j, axis=0)
        # Local trailing update, columns with global index > j
        # (_householder_inner! semantics, src:198-213).
        w = jnp.matmul(jnp.conj(v), Al, precision=precision)
        w = jnp.where(gidx > j, w, jnp.zeros_like(w))
        Al = Al - v[:, None] * w[None, :]
        return Al, alpha

    alpha0 = jnp.zeros((n,), dtype=Al.dtype)
    return lax.fori_loop(0, n, step, (Al, alpha0))


def _blocked_shard_body(
    Al, *, n: int, nb: int, axis: str,
    precision: str = DEFAULT_PRECISION, layout: str = "block",
    norm: str = "accurate", pallas: bool = False, pallas_interpret: bool = False,
    panel_impl: str = "loop", pallas_flat: "int | None" = None,
    trailing_precision: "str | None" = None, lookahead: bool = False,
    agg_panels: "int | None" = None, overlap_depth: "int | None" = None,
    comms: "str | None" = None,
):
    """Per-device body for the compact-WY engine.

    Program size is bounded the same way as the single-device engine
    (ops/blocked.py): few panels -> fully-unrolled shrinking slices; many
    panels -> outer Python loop over <= MAX_UNROLLED_PANELS statically
    row-sliced super-blocks with a ``lax.scan`` over uniform panels inside
    (one psum per panel either way — the reference's per-column broadcast,
    src:141-143, batched nb columns at a time).
    """
    m, nloc = Al.shape
    p = _topo.axis_index(axis)
    nproc = n // nloc
    gidx_base = _local_gidx(p, n, nloc, nb, layout)
    alpha = jnp.zeros((n,), dtype=Al.dtype)
    num_panels = n // nb  # nb | nloc and n = nproc * nloc (checked by callers)
    # Trailing-update GEMM precision may be split from the panel/T-factor
    # precision — same contract as the single-device engine (blocked.py).
    tprec = precision if trailing_precision is None else trailing_precision

    # Static local-column shrinkage ("drop"): with the cyclic layout, by the
    # time panel kb starts, every device's first kb // nproc stored blocks
    # are fully factored (device p's stored block l holds global panel
    # l*nproc + p, done iff l*nproc + p < kb, and l < kb // nproc implies
    # that for every p) — so they can be sliced off the trailing update
    # statically instead of masked, cutting the dead flops the masking
    # would otherwise spend. The block layout has no p-independent done
    # prefix (low-p devices simply go idle — that is why cyclic exists).
    def _done_cols(kb: int) -> int:
        return (kb // nproc) * nb if layout == "cyclic" else 0

    def _factor(panel, off):
        if pallas:
            from dhqr_tpu.ops.blocked import _panel_factor_pallas

            return _panel_factor_pallas(panel, off, precision,
                                        pallas_interpret, base=pallas_flat)
        from dhqr_tpu.ops.blocked import _panel_factor

        return _panel_factor(panel, off, precision, norm, panel_impl)

    def _psum_owner(x, mine):
        return _wire.wire_psum(jnp.where(mine, x, jnp.zeros_like(x)),
                               axis, comms)

    if agg_panels and agg_panels > 1 and num_panels > 1:
        # With lookahead too, this is the GROUPED-lookahead composition
        # (mesh-only — see _blocked_shard_agg).
        return _blocked_shard_agg(
            Al, n=n, nb=nb, k=agg_panels, axis=axis, precision=precision,
            layout=layout, factor=_factor, done_cols=_done_cols, tprec=tprec,
            gidx_base=gidx_base, p=p, nproc=nproc, lookahead=lookahead,
            comms=comms,
        )

    if (lookahead and overlap_depth and num_panels > 1
            and min(overlap_depth, num_panels - 1) > 1):
        return _blocked_shard_pipeline(
            Al, n=n, nb=nb, depth=min(overlap_depth, num_panels - 1),
            axis=axis, precision=precision, layout=layout, factor=_factor,
            psum_owner=_psum_owner, done_cols=_done_cols, tprec=tprec,
            gidx_base=gidx_base, p=p, nproc=nproc,
        )

    if lookahead and num_panels > 1:
        return _blocked_shard_lookahead(
            Al, n=n, nb=nb, axis=axis, precision=precision, layout=layout,
            factor=_factor, psum_owner=_psum_owner, done_cols=_done_cols,
            tprec=tprec, gidx_base=gidx_base, p=p, nproc=nproc,
        )

    if num_panels <= MAX_UNROLLED_PANELS:
        for k in range(0, n, nb):
            b = min(nb, n - k)
            owner, kl = _panel_owner(k, n, nloc, nb, layout)  # static placement
            mine = p == owner
            # Every device factors its own (m-k, b) slice; the psum keeps the
            # owner's result. SPMD-friendly redundant compute beats a branch.
            panel = lax.slice(Al, (k, kl), (m, kl + b))  # rows k:, offset 0
            # gate validated once in sharded_blocked_qr against the FLAT
            # width (panels wider than pallas_flat split into base-width
            # kernel calls); the VMEM budget is monotone in (m, nb), so
            # every smaller panel fits too
            if pallas:
                from dhqr_tpu.ops.blocked import _panel_factor_pallas

                pf, alpha_k = _panel_factor_pallas(
                    panel, 0, precision, pallas_interpret, base=pallas_flat
                )
            else:
                from dhqr_tpu.ops.blocked import _panel_factor

                pf, alpha_k = _panel_factor(panel, 0, precision, norm,
                                            panel_impl)
            zero = jnp.zeros_like(pf)
            pf = _wire.wire_psum(jnp.where(mine, pf, zero), axis, comms)
            alpha_k = _wire.wire_psum(
                jnp.where(mine, alpha_k, jnp.zeros_like(alpha_k)), axis,
                comms
            )
            alpha = alpha.at[k : k + b].set(alpha_k)
            # Owner writes the factored panel back into its block.
            Al_upd = Al.at[k:, kl : kl + b].set(pf)
            Al = jnp.where(mine, Al_upd, Al)
            # Replicated trailing transform: C <- (I - Y T^H Y^H) C on local
            # columns right of the panel (masked), rows k:m.
            drop = _done_cols(k // nb)
            Y = jnp.tril(pf)  # (m-k, b); zeros above row k handled by slicing
            C = lax.slice(Al, (k, drop), (m, nloc))
            C_new = apply_block_reflector_h(Y, C, precision,
                                            gemm_precision=tprec)
            cmask = (gidx_base[drop:] >= k + b)[None, :]
            Al = Al.at[k:, drop:].set(jnp.where(cmask, C_new, C))
        return Al, alpha

    _, _, ppo = _panels_schedule(n, nb)  # panels per super-block (rem 0 here)
    for ob in range(0, num_panels, ppo):
        pcount = min(ppo, num_panels - ob)
        K = ob * nb
        drop = _done_cols(ob)  # static: columns done before this super-block
        Sl = lax.slice(Al, (K, drop), (m, nloc))  # rows K:, live local columns
        def body(Sl, q, ob=ob, ms=m - K, K=K, drop=drop, blk_pallas=pallas):
            kb = ob + q              # global panel index (traced)
            k = kb * nb              # global start column
            c = k - K                # row offset within the super-block
            owner, kl = _panel_owner_traced(kb, nproc, nloc, nb, layout)
            kl = kl - drop           # local offset within the live slice
            mine = p == owner
            panel = lax.dynamic_slice(Sl, (jnp.int32(0), kl), (ms, nb))
            if blk_pallas:
                from dhqr_tpu.ops.blocked import _panel_factor_pallas

                pf, alpha_k = _panel_factor_pallas(
                    panel, c, precision, pallas_interpret, base=pallas_flat
                )
            else:
                from dhqr_tpu.ops.blocked import _panel_factor

                pf, alpha_k = _panel_factor(panel, c, precision, norm,
                                            panel_impl)
            pf = _wire.wire_psum(jnp.where(mine, pf, jnp.zeros_like(pf)),
                                 axis, comms)
            alpha_k = _wire.wire_psum(
                jnp.where(mine, alpha_k, jnp.zeros_like(alpha_k)), axis,
                comms
            )
            Sl_upd = lax.dynamic_update_slice(Sl, pf, (jnp.int32(0), kl))
            Sl = jnp.where(mine, Sl_upd, Sl)
            Y = shifted_tril(pf, c)
            C_new = apply_block_reflector_h(Y, Sl, precision,
                                            gemm_precision=tprec)
            cmask = (gidx_base[drop:] >= k + nb)[None, :]
            Sl = jnp.where(cmask, C_new, Sl)
            return Sl, alpha_k

        Sl, alpha_blk = lax.scan(body, Sl, jnp.arange(pcount, dtype=jnp.int32))
        Al = Al.at[K:, drop:].set(Sl)
        alpha = alpha.at[K : K + pcount * nb].set(alpha_blk.reshape(pcount * nb))
    return Al, alpha


def _blocked_shard_lookahead(
    Al, *, n, nb, axis, precision, layout, factor, psum_owner, done_cols,
    tprec, gidx_base, p, nproc,
):
    """One-panel-lookahead order for the sharded compact-WY body.

    Same arithmetic per column as the default order (panel transforms
    applied in sequence), but panel k+1 is factored — and its psum issued
    — BEFORE panel k's wide local trailing GEMM, whose inputs do not
    depend on that psum. XLA's latency-hiding scheduler can then overlay
    the collective (the reference's dominant cost: the per-panel reflector
    broadcast, src:141-143) with the trailing MXU work instead of
    serializing psum -> GEMM -> psum every panel. Program-size strategy
    matches :func:`_blocked_shard_body`: unrolled below
    MAX_UNROLLED_PANELS, else super-blocks with an inner scan (the
    super-block boundary is a one-panel bubble, handled by a fix-up apply
    after each scan).
    """
    m, nloc = Al.shape
    num_panels = n // nb
    alpha = jnp.zeros((n,), dtype=Al.dtype)

    if num_panels <= MAX_UNROLLED_PANELS:
        owner0, kl0 = _panel_owner(0, n, nloc, nb, layout)
        mine0 = p == owner0
        with jax.named_scope("panel_factor"):
            pf, a0 = factor(lax.slice(Al, (0, kl0), (m, kl0 + nb)), 0)
            pf = psum_owner(pf, mine0)
            a0 = psum_owner(a0, mine0)
        alpha = alpha.at[:nb].set(a0)
        Al = jnp.where(mine0, Al.at[:, kl0 : kl0 + nb].set(pf), Al)
        kp = 0  # pending panel's start column; pf is diag-framed (rows kp:)
        for k1 in range(nb, n, nb):
            owner1, kl1 = _panel_owner(k1, n, nloc, nb, layout)
            mine1 = p == owner1
            Y = jnp.tril(pf)
            with jax.named_scope("lookahead_update"):
                C1 = lax.slice(Al, (kp, kl1), (m, kl1 + nb))
                C1 = apply_block_reflector_h(Y, C1, precision,
                                             gemm_precision=tprec)
            with jax.named_scope("panel_factor"):
                pf1, a1 = factor(C1, nb)  # diag at offset nb = k1 - kp
                pf1 = psum_owner(pf1, mine1)
                a1 = psum_owner(a1, mine1)
            alpha = alpha.at[k1 : k1 + nb].set(a1)
            drop = done_cols(kp // nb)
            with jax.named_scope("trailing_update"):
                # Reads Al BEFORE the pf1 write: the wide GEMM must not
                # depend on panel k1's psum (disjoint column sets — the
                # mask excludes panel k1, so the writes commute).
                C = lax.slice(Al, (kp, drop), (m, nloc))
                C_new = apply_block_reflector_h(Y, C, precision,
                                                gemm_precision=tprec)
                cmask = (gidx_base[drop:] >= k1 + nb)[None, :]
                Al = Al.at[kp:, drop:].set(jnp.where(cmask, C_new, C))
            Al = jnp.where(mine1,
                           Al.at[kp:, kl1 : kl1 + nb].set(pf1), Al)
            # Carry pending in its own row frame (rows k1:, diag at 0).
            pf = lax.slice(pf1, (nb, 0), (m - kp, nb))
            kp = k1
        return Al, alpha

    _, _, ppo = _panels_schedule(n, nb)
    for ob in range(0, num_panels, ppo):
        pcount = min(ppo, num_panels - ob)
        K = ob * nb
        drop = done_cols(ob)  # static: done before this super-block
        Sl = lax.slice(Al, (K, drop), (m, nloc))
        ms = m - K
        owner0, kl0 = _panel_owner(K, n, nloc, nb, layout)
        kl0 -= drop
        mine0 = p == owner0
        with jax.named_scope("panel_factor"):
            pf0, a0 = factor(lax.slice(Sl, (0, kl0), (ms, kl0 + nb)), 0)
            pf0 = psum_owner(pf0, mine0)
            a0 = psum_owner(a0, mine0)
        Sl = jnp.where(mine0, Sl.at[:, kl0 : kl0 + nb].set(pf0), Sl)
        alpha = alpha.at[K : K + nb].set(a0)

        def body(carry, q, ob=ob, ms=ms, K=K, drop=drop):
            Sl, pf = carry  # pf: full super-block height, diag at q*nb
            kb1 = ob + q + 1
            k1 = kb1 * nb
            c1 = k1 - K
            c = c1 - nb
            owner1, kl1 = _panel_owner_traced(kb1, nproc, nloc, nb, layout)
            kl1 = kl1 - drop
            mine1 = p == owner1
            Y = shifted_tril(pf, c)
            with jax.named_scope("lookahead_update"):
                C1 = lax.dynamic_slice(Sl, (jnp.int32(0), kl1), (ms, nb))
                C1 = apply_block_reflector_h(Y, C1, precision,
                                             gemm_precision=tprec)
            with jax.named_scope("panel_factor"):
                pf1, a1 = factor(C1, c1)
                pf1 = psum_owner(pf1, mine1)
                a1 = psum_owner(a1, mine1)
            with jax.named_scope("trailing_update"):
                # Pre-pf1 Sl, as above: keep the wide GEMM independent of
                # panel q+1's psum so the scheduler can overlap them.
                C_new = apply_block_reflector_h(Y, Sl, precision,
                                                gemm_precision=tprec)
                cmask = (gidx_base[drop:] >= k1 + nb)[None, :]
                Sl = jnp.where(cmask, C_new, Sl)
            Sl_upd = lax.dynamic_update_slice(Sl, pf1, (jnp.int32(0), kl1))
            Sl = jnp.where(mine1, Sl_upd, Sl)
            return (Sl, pf1), a1

        (Sl, pf_last), a_rest = lax.scan(
            body, (Sl, pf0), jnp.arange(pcount - 1, dtype=jnp.int32))
        with jax.named_scope("trailing_update"):
            c = (pcount - 1) * nb
            Y = shifted_tril(pf_last, c)
            C_new = apply_block_reflector_h(Y, Sl, precision,
                                            gemm_precision=tprec)
            cmask = (gidx_base[drop:] >= K + pcount * nb)[None, :]
            Sl = jnp.where(cmask, C_new, Sl)
        Al = Al.at[K:, drop:].set(Sl)
        if pcount > 1:
            alpha = alpha.at[K + nb : K + pcount * nb].set(
                a_rest.reshape((pcount - 1) * nb))
    return Al, alpha


def _blocked_shard_pipeline(
    Al, *, n, nb, depth, axis, precision, layout, factor, psum_owner,
    done_cols, tprec, gidx_base, p, nproc,
):
    """Depth-``depth`` pipelined panel-broadcast order (dhqr-pipeline).

    Generalizes :func:`_blocked_shard_lookahead` — exactly the ``depth=1``
    member of this family — to a double-buffered ring of up to ``depth``
    factored pending panels: panel q's psum is issued ``depth`` panels
    BEFORE the wide trailing GEMM that consumes it, so the latency-hiding
    scheduler holds ``depth`` wide compact-WY GEMMs of MXU work to overlay
    on every collective instead of one. Per-column arithmetic is identical
    to the lookahead order by construction: a column in panel j receives
    transforms j-depth..j-1 through narrow single-panel applies (the same
    row frames the lookahead order uses for its one narrow apply) and
    transforms < j-depth through the wide masked applies, in ascending
    order either way. Collective count is unchanged (two one-hot psums
    per panel: pf + alpha) and the psums still route through the wire
    seam, so the bf16/int8 rungs pipeline too; the pf psum frame grows by
    at most ``depth*nb`` rows of already-final R (the lookahead order
    already ships ``nb`` of them), which the blocked_qr contract slack
    absorbs — volume model unchanged. Program-size strategy matches the
    other schedules: unrolled below MAX_UNROLLED_PANELS, else
    super-blocks (rounded up so each holds at least two full pipelines)
    with an inner ``lax.scan`` whose carry stacks the pending ring; each
    super-block boundary is a depth-panel bubble, filled by an unrolled
    startup and drained by masked fix-up applies.
    """
    m, nloc = Al.shape
    num_panels = n // nb
    alpha = jnp.zeros((n,), dtype=Al.dtype)
    # Callers (sharded_blocked_qr) clamp and normalize: depth 1 IS the
    # lookahead order and must resolve to that cached program instead.
    assert 2 <= depth <= num_panels - 1, (depth, num_panels)

    if num_panels <= MAX_UNROLLED_PANELS:
        ring = []  # (k_p, pf_p): pf framed at rows k_p:, diag at 0
        for q1 in range(num_panels):
            k1 = q1 * nb
            owner1, kl1 = _panel_owner(k1, n, nloc, nb, layout)
            mine1 = p == owner1
            k_old = ring[0][0] if ring else k1
            C1 = lax.slice(Al, (k_old, kl1), (m, kl1 + nb))
            for k_p, pf_p in ring:  # oldest -> newest, lookahead frames
                with jax.named_scope("lookahead_update"):
                    sub = lax.slice(C1, (k_p - k_old, 0), (m - k_old, nb))
                    sub = apply_block_reflector_h(
                        jnp.tril(pf_p), sub, precision,
                        gemm_precision=tprec)
                    C1 = C1.at[k_p - k_old:, :].set(sub)
            with jax.named_scope("panel_factor"):
                pf1, a1 = factor(C1, k1 - k_old)
                pf1 = psum_owner(pf1, mine1)
                a1 = psum_owner(a1, mine1)
            alpha = alpha.at[k1 : k1 + nb].set(a1)
            if len(ring) == depth:
                # Wide apply of the OLDEST pending — panel q1's psum
                # (above) is already in flight, as are the depth-1
                # younger pendings'.
                k_p, pf_p = ring.pop(0)
                drop = done_cols(k_p // nb)
                with jax.named_scope("trailing_update"):
                    # Reads Al BEFORE the pf1 write: the wide GEMM must
                    # not depend on any in-flight psum (the mask
                    # excludes every pipelined panel's columns — those
                    # take the narrow path above).
                    C = lax.slice(Al, (k_p, drop), (m, nloc))
                    C_new = apply_block_reflector_h(
                        jnp.tril(pf_p), C, precision, gemm_precision=tprec)
                    cmask = (gidx_base[drop:] >= k1 + nb)[None, :]
                    Al = Al.at[k_p:, drop:].set(jnp.where(cmask, C_new, C))
            Al = jnp.where(mine1,
                           Al.at[k_old:, kl1 : kl1 + nb].set(pf1), Al)
            ring.append((k1, lax.slice(pf1, (k1 - k_old, 0),
                                       (m - k_old, nb))))
        # Drain: every column right of a still-pending panel already
        # received its transform through the narrow applies above —
        # nothing is left to apply once the last panel factors.
        return Al, alpha

    _, _, ppo = _panels_schedule(n, nb)
    # Each super-block must hold at least two full pipelines so the scan
    # has a steady state — the grouped-lookahead order's guard, with the
    # pipeline depth in the group-width role.
    ppo = max(ppo, 2 * depth)
    for ob in range(0, num_panels, ppo):
        pcount = min(ppo, num_panels - ob)
        K = ob * nb
        drop = done_cols(ob)  # static: done before this super-block
        Sl = lax.slice(Al, (K, drop), (m, nloc))
        ms = m - K
        gidx_live = gidx_base[drop:]
        d0 = min(depth, pcount)
        # Startup bubble: fill the ring. Pendings are carried at full
        # super-block height with the diag at (panel - ob)*nb, exactly
        # like the lookahead scan's carry, so the scan below can rotate
        # them through one stacked array.
        ring = []
        for j in range(d0):
            k1 = (ob + j) * nb
            owner1, kl1 = _panel_owner(k1, n, nloc, nb, layout)
            kl1 -= drop
            mine1 = p == owner1
            C1 = lax.slice(Sl, (0, kl1), (ms, kl1 + nb))
            for i, pf_p in enumerate(ring):
                with jax.named_scope("lookahead_update"):
                    C1 = apply_block_reflector_h(
                        shifted_tril(pf_p, i * nb), C1, precision,
                        gemm_precision=tprec)
            with jax.named_scope("panel_factor"):
                pf1, a1 = factor(C1, j * nb)
                pf1 = psum_owner(pf1, mine1)
                a1 = psum_owner(a1, mine1)
            alpha = alpha.at[k1 : k1 + nb].set(a1)
            Sl = jnp.where(mine1, Sl.at[:, kl1 : kl1 + nb].set(pf1), Sl)
            ring.append(pf1)

        nsteps = pcount - d0  # 0 when the last super-block is all bubble
        if nsteps:
            ring_arr = jnp.stack(ring)

            def body(carry, q, ob=ob, ms=ms, K=K, drop=drop):
                Sl, ring = carry  # ring[i]: panel ob+q+i, diag (q+i)*nb
                kb1 = ob + q + depth
                k1 = kb1 * nb
                c1 = k1 - K
                owner1, kl1 = _panel_owner_traced(kb1, nproc, nloc, nb,
                                                  layout)
                kl1 = kl1 - drop
                mine1 = p == owner1
                C1 = lax.dynamic_slice(Sl, (jnp.int32(0), kl1), (ms, nb))
                for i in range(depth):
                    with jax.named_scope("lookahead_update"):
                        C1 = apply_block_reflector_h(
                            shifted_tril(ring[i], c1 - (depth - i) * nb),
                            C1, precision, gemm_precision=tprec)
                with jax.named_scope("panel_factor"):
                    pf1, a1 = factor(C1, c1)
                    pf1 = psum_owner(pf1, mine1)
                    a1 = psum_owner(a1, mine1)
                with jax.named_scope("trailing_update"):
                    # Pre-write Sl, as in the lookahead scan: the wide
                    # GEMM consumes only the OLDEST pending and must not
                    # depend on any of the depth in-flight psums.
                    C_new = apply_block_reflector_h(
                        shifted_tril(ring[0], c1 - depth * nb), Sl,
                        precision, gemm_precision=tprec)
                    cmask = (gidx_live >= k1 + nb)[None, :]
                    Sl = jnp.where(cmask, C_new, Sl)
                Sl_upd = lax.dynamic_update_slice(Sl, pf1,
                                                  (jnp.int32(0), kl1))
                Sl = jnp.where(mine1, Sl_upd, Sl)
                ring = jnp.concatenate([ring[1:], pf1[None]], axis=0)
                return (Sl, ring), a1

            (Sl, ring_arr), a_rest = lax.scan(
                body, (Sl, ring_arr), jnp.arange(nsteps, dtype=jnp.int32))
            alpha = alpha.at[K + d0 * nb : K + pcount * nb].set(
                a_rest.reshape(nsteps * nb))
            ring = [ring_arr[i] for i in range(depth)]
        # Drain the boundary bubble: the remaining pendings' transforms
        # reach every column past this super-block through masked fix-up
        # applies, oldest first (pending i is panel ob+pcount-len+i).
        for i, pf_p in enumerate(ring):
            with jax.named_scope("trailing_update"):
                c = (pcount - len(ring) + i) * nb
                C_new = apply_block_reflector_h(
                    shifted_tril(pf_p, c), Sl, precision,
                    gemm_precision=tprec)
                cmask = (gidx_live >= K + pcount * nb)[None, :]
                Sl = jnp.where(cmask, C_new, Sl)
        Al = Al.at[K:, drop:].set(Sl)
    return Al, alpha


def _blocked_shard_agg(
    Al, *, n, nb, k, axis, precision, layout, factor, done_cols,
    tprec, gidx_base, p, nproc, lookahead=False, comms=None,
):
    """Aggregated-trailing-update order for the sharded compact-WY body.

    The sharded twin of ``ops.blocked._scan_panels_grouped``, with a
    collectives twist that only exists on the mesh: instead of one psum per
    panel (k per group — the batched form of the reference's per-column
    reflector broadcast, src:141-143), the group's k*nb columns are
    gathered with ONE psum. That moves the same total words over ICI in
    1/k as many collective launches, and — because the gathered group is
    then replicated — every device can factor the WHOLE group redundantly
    with zero further communication (bit-identical inputs give
    bit-identical panels; redundant compute already being the body's
    idiom, see :func:`_blocked_shard_body`). The wide local trailing
    update then runs once per group with the aggregated tau=1 compact-WY
    transform (``shifted_tril`` of the k packed panels side by side), so
    wide passes drop k-fold exactly as on the single-device tier.

    ``lookahead=True`` composes GROUPED lookahead on top (mesh-only —
    the single-device tiers keep rejecting the combination, where both
    knobs only add flops): group g+1's gather psum is issued, its
    replicated copy updated by group g's aggregated transform, and its
    factorization completed BEFORE group g's wide local trailing GEMM,
    whose inputs deliberately do not depend on that psum — 1/k the
    collective launches AND a full wide-GEMM overlap window per
    collective. Per-column arithmetic is order-identical to the plain
    aggregated schedule.

    Program-size strategy matches the default body: groups statically
    unrolled below MAX_UNROLLED_PANELS panels (plain schedule; the
    lookahead composition always uses the super-block machinery — its
    pending-group carry wants uniform frames), else super-blocks with an
    inner ``lax.scan`` over groups (the super-block size is rounded up to
    a multiple of k so aggregation always engages; a final sub-k panel
    remainder runs as ONE ragged aggregated group — single gather psum —
    unlike ops/blocked's single-device remainder, which falls back to the
    per-panel scan). Under lookahead each super-block boundary is a
    one-group bubble, exactly like the panel-lookahead scan's.
    """
    m, nloc = Al.shape
    num_panels = n // nb
    alpha = jnp.zeros((n,), dtype=Al.dtype)
    W = k * nb

    def _norm(owners):
        return [(mine, jnp.asarray(kl, jnp.int32)) for mine, kl in owners]

    def gather(Sl, owners, width):
        """One psum: owners contribute their panels one-hot, replicated."""
        ms = Sl.shape[0]
        with jax.named_scope("group_gather"):
            contrib = jnp.zeros((ms, width), dtype=Sl.dtype)
            for j, (mine, kl) in enumerate(owners):
                loc = lax.dynamic_slice(Sl, (jnp.int32(0), kl), (ms, nb))
                contrib = lax.dynamic_update_slice(
                    contrib, jnp.where(mine, loc, jnp.zeros_like(loc)),
                    (jnp.int32(0), jnp.int32(j * nb)))
            # One-hot per column block: the psum adds zeros, so the
            # wire format never touches the accumulation.
            return _wire.wire_psum(contrib, axis, comms)

    def scatter(Sl, G, owners):
        """Owners write their factored panels back into the local slice."""
        ms = Sl.shape[0]
        for j, (mine, kl) in enumerate(owners):
            pfj = lax.slice(G, (0, j * nb), (ms, (j + 1) * nb))
            Sl_upd = lax.dynamic_update_slice(Sl, pfj, (jnp.int32(0), kl))
            Sl = jnp.where(mine, Sl_upd, Sl)
        return Sl

    def wide_apply(Sl, G, c0, gidx_live, end_col):
        """Aggregated trailing transform on local columns >= end_col."""
        with jax.named_scope("trailing_update_agg"):
            Yg = shifted_tril(G, c0)
            C_new = apply_block_reflector_h(Yg, Sl, precision,
                                            gemm_precision=tprec)
            cmask = (gidx_live >= end_col)[None, :]
            return jnp.where(cmask, C_new, Sl)

    def group(Sl, c0, gsize, owners, gidx_live, end_col):
        """Factor one gsize-panel group on the live slice Sl (ms, ncols).

        ``c0``: diag row offset of the group within Sl (traced in scans);
        ``owners``: per-panel (mine, local col offset) pairs;
        ``end_col``: global column index just past the group (mask bound).
        Returns the updated slice and the group's stacked alpha block.
        """
        owners = _norm(owners)
        G = gather(Sl, owners, gsize * nb)
        G, alphas = _factor_group(G, c0, gsize, nb, factor, precision,
                                  tprec)
        Sl = scatter(Sl, G, owners)
        Sl = wide_apply(Sl, G, c0, gidx_live, end_col)
        return Sl, alphas

    if num_panels <= MAX_UNROLLED_PANELS and not (lookahead
                                                  and num_panels > k):
        for g0 in range(0, num_panels, k):
            gsize = min(k, num_panels - g0)
            k0 = g0 * nb
            drop = done_cols(g0)
            owners = []
            for j in range(gsize):
                ow, kl = _panel_owner(k0 + j * nb, n, nloc, nb, layout)
                owners.append((p == ow, kl - drop))
            Sl = lax.slice(Al, (k0, drop), (m, nloc))
            Sl, a_grp = group(Sl, 0, gsize, owners, gidx_base[drop:],
                              k0 + gsize * nb)
            Al = Al.at[k0:, drop:].set(Sl)
            alpha = alpha.at[k0 : k0 + gsize * nb].set(a_grp)
        return Al, alpha

    _, _, ppo = _panels_schedule(n, nb)
    # Round the super-block UP to a multiple of k so every super-block
    # holds whole groups and aggregation genuinely engages (same guard as
    # the single-device dispatch, ops/blocked._blocked_qr_impl); under
    # lookahead, to at least TWO groups, or no super-block ever holds a
    # pending/next pair and the composition silently degenerates to the
    # plain aggregated order.
    ppo = -(-ppo // k) * k
    if lookahead:
        ppo = max(ppo, 2 * k)
    for ob in range(0, num_panels, ppo):
        pcount = min(ppo, num_panels - ob)
        K = ob * nb
        drop = done_cols(ob)
        Sl = lax.slice(Al, (K, drop), (m, nloc))
        ms = m - K
        gidx_live = gidx_base[drop:]
        ngroups, rem = pcount // k, pcount % k

        def _owners_traced(kb0):
            owners = []
            for j in range(k):
                ow, kl = _panel_owner_traced(kb0 + j, nproc, nloc, nb, layout)
                owners.append((p == ow, kl - drop))
            return owners

        def body(Sl, g, ob=ob, K=K):
            kb0 = ob + g * k
            return group(Sl, kb0 * nb - K, k, _owners_traced(kb0),
                         gidx_live, (kb0 + k) * nb)

        if lookahead and ngroups >= 2:
            # Grouped lookahead: group 0 factors up front (wide apply
            # deferred); each scan step gathers+factors group g BEFORE
            # group g-1's wide GEMM; a fix-up applies the last group.
            owners0 = _norm(_owners_traced(jnp.int32(ob)))
            with jax.named_scope("panel_factor"):
                G0 = gather(Sl, owners0, W)
                G0, a0 = _factor_group(G0, ob * nb - K, k, nb, factor,
                                       precision, tprec)
            Sl = scatter(Sl, G0, owners0)
            alpha = alpha.at[K : K + W].set(a0)

            def la_body(carry, g, ob=ob, K=K):
                Sl, Gp = carry  # previous group's factored block (ms, W)
                kb0 = ob + g * k
                c0 = kb0 * nb - K
                owners = _norm(_owners_traced(kb0))
                Gr = gather(Sl, owners, W)  # psum issued EARLY
                with jax.named_scope("lookahead_update"):
                    Yp = shifted_tril(Gp, c0 - W)
                    Gr = apply_block_reflector_h(Yp, Gr, precision,
                                                 gemm_precision=tprec)
                with jax.named_scope("panel_factor"):
                    G, a_g = _factor_group(Gr, c0, k, nb, factor,
                                           precision, tprec)
                with jax.named_scope("trailing_update"):
                    # Pre-scatter Sl: the wide GEMM must not depend on
                    # THIS group's psum (the mask excludes this group's
                    # columns, which the scatter below writes).
                    C_new = apply_block_reflector_h(Yp, Sl, precision,
                                                    gemm_precision=tprec)
                    cmask = (gidx_live >= (kb0 + k) * nb)[None, :]
                    Sl = jnp.where(cmask, C_new, Sl)
                Sl = scatter(Sl, G, owners)
                return (Sl, G), a_g

            (Sl, G_last), a_rest = lax.scan(
                la_body, (Sl, G0),
                jnp.arange(1, ngroups, dtype=jnp.int32))
            Sl = wide_apply(Sl, G_last, (ob + (ngroups - 1) * k) * nb - K,
                            gidx_live, (ob + ngroups * k) * nb)
            alpha = alpha.at[K + W : K + ngroups * W].set(
                a_rest.reshape((ngroups - 1) * W))
        elif ngroups:
            Sl, a_grp = lax.scan(body, Sl,
                                 jnp.arange(ngroups, dtype=jnp.int32))
            alpha = alpha.at[K : K + ngroups * k * nb].set(
                a_grp.reshape(ngroups * k * nb))
        # Sub-k remainder (last super-block only, at most k-1 panels): one
        # ragged group — static placement, and it keeps the
        # one-gather-psum win exactly like the unrolled tier's final group.
        if rem:
            kg0 = (ob + ngroups * k) * nb
            owners = []
            for r in range(rem):
                ow, kl = _panel_owner(kg0 + r * nb, n, nloc, nb, layout)
                owners.append((p == ow, kl - drop))
            Sl, a_rem = group(Sl, kg0 - K, rem, owners, gidx_live,
                              kg0 + rem * nb)
            alpha = alpha.at[kg0 : kg0 + rem * nb].set(a_rem)
        Al = Al.at[K:, drop:].set(Sl)
    return Al, alpha


@lru_cache(maxsize=None)
def _build_unblocked(
    mesh: Mesh, axis_name: str, n: int, precision: str, layout: str,
    store_nb: int, norm: str = "accurate", comms: "str | None" = None,
    seam=None,
):
    # ``seam``: round-19 cache-key material only (wire.seam_token) —
    # None in the common case, a fresh tuple per fault epoch / armor
    # re-arm so trace-time injection and tag programs re-trace.
    body = partial(
        _unblocked_shard_body,
        n=n, axis=axis_name, precision=precision, layout=layout,
        store_nb=store_nb, norm=norm, comms=comms,
    )
    spec = _topo.spec_axes(axis_name)
    return jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=P(None, spec),
            out_specs=(P(None, spec), P()),
            check_vma=False,  # alpha is replicated by construction (psum inputs)
        )
    )


@lru_cache(maxsize=None)
def _build_blocked(
    mesh: Mesh, axis_name: str, n: int, nb: int, precision: str, layout: str,
    norm: str = "accurate", pallas: bool = False, pallas_interpret: bool = False,
    panel_impl: str = "loop", pallas_flat: "int | None" = None,
    trailing_precision: "str | None" = None, lookahead: bool = False,
    agg_panels: "int | None" = None, overlap_depth: "int | None" = None,
    comms: "str | None" = None, seam=None,
):
    # ``seam``: round-19 cache-key material only (see _build_unblocked).
    body = partial(
        _blocked_shard_body,
        n=n, nb=nb, axis=axis_name, precision=precision, layout=layout,
        norm=norm, pallas=pallas, pallas_interpret=pallas_interpret,
        panel_impl=panel_impl, pallas_flat=pallas_flat,
        trailing_precision=trailing_precision, lookahead=lookahead,
        agg_panels=agg_panels, overlap_depth=overlap_depth, comms=comms,
    )
    spec = _topo.spec_axes(axis_name)
    return jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=P(None, spec),
            out_specs=(P(None, spec), P()),
            check_vma=False,
        )
    )


def _pad_cols_orthogonal(A, n_pad: int):
    """Extend A (m, n) to (m + k, n_pad), k = n_pad - n, as [[A, 0], [0, I_k]].

    The padded columns live entirely in the padded rows, so they are exactly
    orthogonal to the originals, and the padded factorization contains the
    true one as its leading [:m, :n] sub-block, exactly in exact arithmetic
    (numerically to ~ulp — padding changes reduction-tree shapes only):

    * a right-looking QR's result for column j depends only on columns <= j,
      so the leading n columns' reflectors and alpha are untouched;
    * the original reflectors vanish on the padded rows (their columns are
      zero there), so Q's leading n columns vanish there too, making the
      R coupling block R[:n, n:] = Q[:, :n]^H A_pad[:, n:] exactly zero —
      back-substitution of the padded R never mixes padded entries into
      x[:n];
    * the padded columns' reflectors vanish on the original rows, so
      slicing [:m, :n] loses nothing.

    This is the TPU-native answer to arbitrary problem shapes, replacing
    the reference's *uneven* worker blocks (``columnblocks`` src:18-19;
    sqrt-split, test/runtests.jl:36-38): XLA shardings are even by
    construction, so the matrix is padded to the layout's divisibility and
    the results sliced back (VERDICT r2 next-round #3).
    """
    m, n = A.shape
    k = n_pad - n
    if k == 0:
        return A
    top = jnp.concatenate([A, jnp.zeros((m, k), A.dtype)], axis=1)
    bot = jnp.concatenate(
        [jnp.zeros((k, n), A.dtype), jnp.eye(k, dtype=A.dtype)], axis=1
    )
    return jnp.concatenate([top, bot], axis=0)


def _to_store_layout(A, n, nproc, nb, layout):
    """Permute natural columns into the layout's storage order (no-op for block)."""
    if layout == "block":
        return A
    from dhqr_tpu.parallel.layout import cyclic_store_columns

    return jnp.take(A, jnp.asarray(cyclic_store_columns(n, nproc, nb)), axis=1)


def _to_natural_layout(H, n, nproc, nb, layout):
    """Inverse of :func:`_to_store_layout` on the factored output."""
    if layout == "block":
        return H
    from dhqr_tpu.parallel.layout import natural_store_positions

    return jnp.take(H, jnp.asarray(natural_store_positions(n, nproc, nb)), axis=1)


def sharded_householder_qr(
    A: jax.Array,
    mesh: Mesh,
    axis_name: str = DEFAULT_AXIS,
    precision: str = DEFAULT_PRECISION,
    layout: str = "block",
    store_nb: int = 1,
    _store_layout_output: bool = False,
    norm: str = "accurate",
    comms: "str | None" = None,
):
    """Unblocked distributed QR: ``(H, alpha)`` with H column-sharded.

    One psum per column — the compiled-program equivalent of the reference's
    ``householder!(A::DArray, α)`` control flow (src:115-120) without any
    host round-trips. ``alpha`` is returned replicated (the reference keeps
    it in a ``SharedArray``, src:302).

    ``layout="cyclic"`` distributes columns round-robin so every device owns
    live columns until the sweep ends — the load-balancing role of the
    reference's uneven sqrt-split blocks (test/runtests.jl:36-38). H is
    returned in natural column order unless ``_store_layout_output``
    (``store_nb`` sets the cyclic store's block width so a downstream solve
    can share the storage order — see ``lstsq``'s unblocked mesh path).
    """
    comms = _wire.resolve_comms(comms)
    m, n = A.shape
    axis_name = _topo.resolve_axis(mesh, axis_name)
    nproc = _topo.axis_size(mesh, axis_name)
    ptag = _topo.axis_label(axis_name, nproc)
    if layout == "block":
        store_nb = 1  # unused by the block layout; normalize the cache key
    # Arbitrary n: pad to the layout's divisibility (multiple of store_nb *
    # nproc covers both constraints below), factor, slice back — exact, see
    # :func:`_pad_cols_orthogonal`.
    step = store_nb * nproc
    n_pad = -(-n // step) * step
    if n_pad != n:
        if _store_layout_output:
            raise ValueError(
                f"internal store-layout chaining requires n divisible by "
                f"{step}, got n={n}: pad the input before chaining"
            )
        H, alpha = sharded_householder_qr(
            _pad_cols_orthogonal(A, n_pad), mesh, axis_name=axis_name,
            precision=precision, layout=layout, store_nb=store_nb, norm=norm,
            comms=comms,
        )
        return H[:m, :n], alpha[:n]
    if n > 512:
        # After the padding dispatch, so awkward n warns exactly once.
        import warnings

        warnings.warn(
            f"unblocked sharded engine runs one m-vector collective per "
            f"column (n={n}): this is the reference-faithful slow tier (its "
            "author's own 'this is most expensive', src:141) — use the "
            "blocked compact-WY engine (blocked=True, the default) at scale",
            stacklevel=2,
        )
    # (store_nb | n // nproc holds by construction here: the padding
    # dispatch above guarantees n % (store_nb * nproc) == 0.)
    _check_divisibility(m, n, nproc, None, layout)
    A_in = A
    base_label = f"unblocked_qr[P={ptag},{m}x{n},{layout}]"
    comms = _armor.effective_comms(base_label, comms)
    A = _to_store_layout(A, n, nproc, store_nb, layout)
    A = jax.device_put(A, column_sharding(mesh, axis_name))

    def _dispatch(wire_comms):
        fn = _build_unblocked(
            mesh, axis_name, n, precision, layout, store_nb, norm,
            wire_comms, _wire.seam_token(wire_comms)
        )
        if _pulse.active() is None:
            return fn(A)
        return _pulse.observed_dispatch(
            f"unblocked_qr[P={ptag},{m}x{n},{layout}"
            + (f",w{wire_comms}" if wire_comms else "") + "]",
            lambda: fn(A), abstract=lambda: jax.make_jaxpr(fn)(A),
            n_devices=nproc, wire_format=wire_comms)

    if _armor.active() is None or _store_layout_output:
        # Internal store-layout chaining (sharded_lstsq) verifies once,
        # at the top level, over the whole factor+solve pipeline.
        H, alpha = _dispatch(comms)
    else:
        # Armed branch = natural-layout output only: one relayout per
        # attempt, shared by verify and the caller (see blocked twin).
        def _dispatch_nat(wire_comms):
            Hs, a = _dispatch(wire_comms)
            return _to_natural_layout(Hs, n, nproc, store_nb, layout), a

        def _verify(out):
            return _armor.checks.qr_gap(out[0], out[1], A_in,
                                        min(32, n), precision="highest")

        return _armor.checked_dispatch(
            base_label, lambda: _dispatch_nat(comms), _verify,
            engine="householder", comms=comms,
            degrade=(lambda: _dispatch_nat(None)) if comms else None,
            shard_of=lambda col: _col_owner(col, n, nproc, store_nb,
                                            layout),
            plan_shape=("qr", m, n, str(A_in.dtype), nproc))
    if not _store_layout_output:
        H = _to_natural_layout(H, n, nproc, store_nb, layout)
    return H, alpha


def sharded_blocked_qr(
    A: jax.Array,
    mesh: Mesh,
    block_size: int = 128,
    axis_name: str = DEFAULT_AXIS,
    precision: str = DEFAULT_PRECISION,
    layout: str = "block",
    _store_layout_output: bool = False,
    norm: str = "accurate",
    use_pallas: str = "auto",
    panel_impl: str = "loop",
    trailing_precision: "str | None" = None,
    lookahead: bool = False,
    agg_panels: "int | None" = None,
    overlap_depth: "int | None" = None,
    comms: "str | None" = None,
    policy=None,
):
    """Compact-WY distributed QR: one psum per panel, GEMM trailing updates.

    The MXU path at scale — SURVEY.md §7 stage 3 layered over stage 2.
    ``layout="cyclic"`` assigns nb-wide panels to devices round-robin (see
    :func:`sharded_householder_qr`); ``_store_layout_output`` keeps H in the
    internal storage order (used by ``sharded_lstsq`` to chain directly into
    the solve without two cross-device column permutes).

    ``lookahead=True`` issues each panel's psum BEFORE the previous
    panel's wide trailing GEMM (one-panel lookahead, same per-column
    arithmetic — see :func:`_blocked_shard_lookahead`), giving the
    scheduler room to overlap the collective with MXU work.

    ``overlap_depth=k`` (with ``lookahead=True``) deepens that window to
    a k-panel pipeline: the NEXT k panels' psums are in flight before
    the oldest pending panel's wide trailing GEMM retires, same
    per-column arithmetic again (see :func:`_blocked_shard_pipeline`).
    Depth 1 IS the lookahead order and resolves to its cached program;
    the depth is statically clamped to ``num_panels - 1``. Mutually
    exclusive with ``agg_panels`` (the grouped order owns its own
    overlap composition).

    ``agg_panels=k`` (k > 1) gathers each k-panel group with ONE psum,
    factors the group replicated, and applies the aggregated compact-WY
    trailing update once per group — 1/k the collective launches and wide
    passes for the same words (see :func:`_blocked_shard_agg`). Combined
    with ``lookahead=True`` it becomes the grouped-lookahead composition
    (each group's single psum issued before the previous group's wide
    GEMM) — allowed HERE, on the mesh, where the overlap has a collective
    to hide; the single-device tiers keep rejecting the pair.

    ``comms`` (usually set via ``policy``) names the collective wire
    format: ``"bf16"``/``"int8"`` compress every panel-broadcast psum
    through :mod:`dhqr_tpu.parallel.wire` (accumulation exact — the
    broadcasts are one-hot), ``None`` keeps the program bit-identical
    to the uncompressed tier.

    ``policy`` (a :class:`dhqr_tpu.precision.PrecisionPolicy`, preset name
    or spec string) sets ``precision``/``trailing_precision``/``comms``
    together, mutually exclusive with passing them explicitly; the
    solve-stage fields (``apply``, ``refine``) do not apply to a
    factor-only entry point and are ignored by contract.
    """
    from dhqr_tpu.precision import (apply_policy_to_comms_arg,
                                    apply_policy_to_factor_args)

    comms = apply_policy_to_comms_arg(policy, comms)
    precision, trailing_precision = apply_policy_to_factor_args(
        policy, precision, trailing_precision,
        default_precision=DEFAULT_PRECISION)
    m, n = A.shape
    axis_name = _topo.resolve_axis(mesh, axis_name)
    nproc = _topo.axis_size(mesh, axis_name)
    ptag = _topo.axis_label(axis_name, nproc)
    if agg_panels is not None and agg_panels < 2:
        raise ValueError(f"agg_panels must be >= 2 (got {agg_panels}); "
                         "use None to disable aggregation")
    if overlap_depth is not None:
        if overlap_depth < 1:
            raise ValueError(
                f"overlap_depth must be >= 1 (got {overlap_depth}); "
                "use None for the default schedule")
        if not lookahead:
            raise ValueError(
                "overlap_depth generalizes the lookahead order and "
                "requires lookahead=True (depth 1 IS the one-panel "
                "lookahead)")
        if agg_panels:
            raise ValueError(
                "overlap_depth composes with the per-panel lookahead "
                "order only; it is mutually exclusive with agg_panels "
                "(the grouped-lookahead composition already overlaps "
                "one full group per collective)")
    if agg_panels and lookahead and nproc == 1:
        # The composition's entire win is hiding the gather psum behind
        # the wide trailing GEMM; a 1-device mesh has no collective to
        # hide, so the pair only adds flops there — the same degenerate
        # case the harness refuses at ndev == 1 (ADVICE r5 item 4). Warn
        # rather than reject: a 1-element mesh is a legitimate test/debug
        # tier, and the result is still correct.
        import warnings

        warnings.warn(
            "agg_panels + lookahead on a 1-device mesh: no collective to "
            "hide, the composition only adds flops (the harness rejects "
            "this pair at ndev == 1); proceeding as the mesh tier",
            stacklevel=2,
        )
    # agg_panels + lookahead together = the grouped-lookahead composition
    # (1/k the collectives AND overlap per collective) — mesh-only; the
    # single-device tiers keep rejecting the pair (no collective to hide).
    from dhqr_tpu.parallel.layout import plan_padding

    nb, n_pad = plan_padding(n, nproc, block_size)
    if n_pad != n:
        # Arbitrary n: pad to nb*P divisibility, factor, slice back — exact,
        # see :func:`_pad_cols_orthogonal`.
        if _store_layout_output:
            raise ValueError(
                f"internal store-layout chaining requires n divisible by "
                f"nb*P = {nb * nproc}, got n={n}: pad the input before chaining"
            )
        H, alpha = sharded_blocked_qr(
            _pad_cols_orthogonal(A, n_pad), mesh, block_size=nb,
            axis_name=axis_name, precision=precision, layout=layout,
            norm=norm, use_pallas=use_pallas, panel_impl=panel_impl,
            trailing_precision=trailing_precision, lookahead=lookahead,
            agg_panels=agg_panels, overlap_depth=overlap_depth,
            comms=comms,
        )
        return H[:m, :n], alpha[:n]
    _check_divisibility(m, n, nproc, nb, layout)
    if overlap_depth is not None:
        # Clamp to the deepest pipeline the panel count supports, then
        # normalize depth <= 1 AWAY so it resolves to the one-panel
        # lookahead's IDENTICAL cached program (same _build_blocked key,
        # same labels: zero extra compiles, bitwise-equal by identity).
        overlap_depth = min(overlap_depth, max(n // nb - 1, 1))
        if overlap_depth <= 1:
            overlap_depth = None
    from dhqr_tpu.ops.blocked import _resolve_pallas

    from dhqr_tpu.ops.blocked import PALLAS_FLAT_WIDTH

    # "auto" resolves against the MESH's device, not the process default
    # backend — a TPU mesh driven from a CPU-default process still gets the
    # kernel (VMEM gate sized by the mesh chip), and a virtual CPU mesh on
    # a TPU host does not (same default as blocked_householder_qr since
    # round 4; "always" on a CPU mesh runs the interpreter, the test
    # vehicle — the returned interpret flag encodes exactly that).
    pallas, interp = _resolve_pallas(use_pallas, m, nb, A.dtype,
                                     device=mesh.devices.flat[0])
    from dhqr_tpu.ops.blocked import _pallas_cache_guard

    sched = (((f"la{overlap_depth}" if overlap_depth else "la")
              if lookahead else "")
             + (f"agg{agg_panels}" if agg_panels else ""))
    base_label = (f"blocked_qr[P={ptag},{m}x{n},nb={nb},{layout}"
                  + (f",{sched}" if sched else "") + "]")
    comms = _armor.effective_comms(base_label, comms)

    def _dispatch(wire_comms):
        with _pallas_cache_guard(interp):
            fn = _build_blocked(
                mesh, axis_name, n, nb, precision, layout, norm, pallas,
                interp, panel_impl, PALLAS_FLAT_WIDTH, trailing_precision,
                lookahead, agg_panels, overlap_depth, wire_comms,
                _wire.seam_token(wire_comms),
            )
            if _pulse.active() is None:
                return fn(A)
            tags = (f",{sched}" if sched else "") + (
                f",w{wire_comms}" if wire_comms else "")
            return _pulse.observed_dispatch(
                f"blocked_qr[P={ptag},{m}x{n},nb={nb},{layout}{tags}]",
                lambda: fn(A), abstract=lambda: jax.make_jaxpr(fn)(A),
                n_devices=nproc, wire_format=wire_comms)

    A_in = A
    A = _to_store_layout(A, n, nproc, nb, layout)
    A = jax.device_put(A, column_sharding(mesh, axis_name))
    if _armor.active() is None or _store_layout_output:
        # Internal chaining (sharded_lstsq) verifies once, at the top.
        H, alpha = _dispatch(comms)
    else:
        # ABFT weighted-checksum verification (round 19): u^H A vs
        # (Q^H u)^H R over the factors the dispatch already produced —
        # O(mn), localizing to the worst column's owner shard. The
        # armed branch is only reached for natural-layout output, so
        # each attempt relayouts ONCE, shared by verify and the caller.
        def _dispatch_nat(wire_comms):
            Hs, a = _dispatch(wire_comms)
            return _to_natural_layout(Hs, n, nproc, nb, layout), a

        def _verify(out):
            return _armor.checks.qr_gap(out[0], out[1], A_in, nb,
                                        precision="highest")

        return _armor.checked_dispatch(
            base_label, lambda: _dispatch_nat(comms), _verify,
            engine="householder", comms=comms,
            degrade=(lambda: _dispatch_nat(None)) if comms else None,
            shard_of=lambda col: _col_owner(col, n, nproc, nb, layout),
            plan_shape=("qr", m, n, str(A_in.dtype), nproc))
    if not _store_layout_output:
        H = _to_natural_layout(H, n, nproc, nb, layout)
    return H, alpha


def _check_divisibility(m, n, nproc, nb, layout="block"):
    if m < n:
        raise ValueError(f"requires m >= n, got {(m, n)}")
    if n % nproc != 0:
        raise ValueError(f"n={n} must be divisible by mesh size {nproc}")
    nloc = n // nproc
    if nb is not None and nloc % nb != 0 and nb < nloc:
        raise ValueError(
            f"panel width {nb} must divide local block width {nloc} "
            f"(or exceed it; pad n or choose block_size accordingly)"
        )
    if nb is not None and nb > nloc:
        raise ValueError(
            f"panel width {nb} wider than local block {nloc}: lower block_size "
            f"to <= {nloc} so each panel has a single owner"
        )


# Comms contract (pinned by dhqr-audit, analysis/comms_pass.py +
# analysis/comms_contracts.json; appended here rather than in the module
# docstring so existing line numbers — and with them the persistent
# compile cache's HLO-metadata keys — stay stable): psum is the ONLY
# collective family either engine may launch — one per column
# (unblocked) or per panel/group (blocked), volume bounded by the
# panel-broadcast budget in analysis/cost_model.py. A gather of the
# trailing matrix, an all_to_all from a layout change, or a replicated
# intermediate past the per-shard working set fails tools/lint.sh
# (DHQR301/302/303) before it can burn a TPU session. With a comms
# wire format the SAME psums cross as bf16/int8 and the compressed
# contracts (blocked/unblocked_qr_wire_*) hold the volume at the wire
# itemsize x tight slack — the >= 1.8x reduction, machine-enforced.
