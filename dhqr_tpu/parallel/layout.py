"""Column layouts and local-block bookkeeping (layer L1 of SURVEY.md §1).

TPU-native counterpart of the reference's index/locality shims and
``LocalColumnBlock`` wrapper (reference src/DistributedHouseholderQR.jl:11-40):
``local_column_block`` gives, per mesh position, the global column offset and
width of the local block — the information ``LocalColumnBlock`` carries as
``Δj``/``colrange`` (src:26-36). Inside ``shard_map`` the block itself is just
the local array; only the offset arithmetic is needed.

Also carries the reference's area-balancing split formula
(test/runtests.jl:36-38) as a documented utility and test oracle. On TPU,
XLA shards in *even* blocks, so load-balancing is instead achieved by a
column-cyclic permutation applied before sharding; the sqrt formula remains
the reference semantics for uneven blocks.
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np


@dataclasses.dataclass(frozen=True)
class ColumnBlock:
    """A device's contiguous block of global columns [start, stop).

    ``start`` plays the role of the reference's ``Δj`` column offset and
    ``range(start, stop)`` its ``colrange`` (src:26-36).
    """

    start: int
    stop: int

    @property
    def width(self) -> int:
        return self.stop - self.start

    def contains(self, j: int) -> bool:
        return self.start <= j < self.stop


def local_column_block(n: int, n_devices: int, device_index: int) -> ColumnBlock:
    """Even column-block layout: the block XLA gives shard ``device_index``.

    Matches ``NamedSharding(mesh, P(None, "cols"))`` placement for n divisible
    by n_devices (the supported case, mirroring the reference's even-block
    ``DArray`` constructor at runtests.jl:71).
    """
    if n % n_devices != 0:
        raise ValueError(
            f"n={n} must divide evenly over {n_devices} devices; pad the matrix"
        )
    w = n // n_devices
    return ColumnBlock(device_index * w, (device_index + 1) * w)


def fit_block_size(nloc: int, requested: int) -> int:
    """Largest panel width <= requested that divides the local block width.

    Keeps the single-owner-per-panel invariant of the sharded compact-WY
    engine without making users hand-tune nb against n/mesh combinations.
    """
    nb = max(1, min(int(requested), nloc))
    while nloc % nb:
        nb -= 1
    return nb


def plan_padding(n: int, n_devices: int, requested_nb: int) -> tuple[int, int]:
    """Pick ``(nb, n_pad)`` so arbitrary n fits the sharded-engine invariants.

    The sharded engines need ``n_pad % (nb * P) == 0`` (every panel has a
    single owner and devices hold equal blocks — see ``_check_divisibility``).
    The reference instead handles awkward n with *uneven* worker blocks
    (``columnblocks``, src:18-19; sqrt-split, test/runtests.jl:36-38); XLA
    shardings are even by construction, so the TPU-native answer is to pad
    (VERDICT r2 next-round #3) — this planner keeps the padding minimal.

    Scans panel widths from ``min(requested_nb, ceil(n/P))`` downward and
    returns the width with the smallest padded n; ties break toward wider
    panels (better MXU utilization), and the scan stops early once the
    padding reaches the theoretical minimum ``ceil(n/P)*P - n``.
    """
    if n <= 0:
        raise ValueError(f"n must be positive, got {n}")
    nloc0 = -(-n // n_devices)  # ceil: local width after minimal padding
    minimal = nloc0 * n_devices
    best_nb = best_pad = None
    for nb in range(min(max(int(requested_nb), 1), nloc0), 0, -1):
        step = nb * n_devices
        n_pad = -(-n // step) * step
        if best_pad is None or n_pad < best_pad:
            best_nb, best_pad = nb, n_pad
        if n_pad == minimal:
            break
    return best_nb, best_pad


def column_block_ranges(n: int, n_devices: int) -> list[ColumnBlock]:
    """All devices' blocks — the reference's ``columnblocks`` table (src:18-19)."""
    return [local_column_block(n, n_devices, p) for p in range(n_devices)]


def cyclic_store_columns(n: int, n_devices: int, nb: int) -> np.ndarray:
    """Column order that makes contiguous sharding a block-cyclic layout.

    ``A[:, cyclic_store_columns(n, P, nb)]`` sharded in contiguous blocks of
    ``n // P`` columns gives device p the global column blocks
    ``{kb : kb % P == p}`` of width nb — the load-balanced layout SURVEY.md
    §2 prescribes in place of the reference's uneven sqrt-split blocks
    (test/runtests.jl:36-38): in the right-looking panel sweep every device
    keeps owning live panels until the end, instead of the leading blocks'
    owners going idle.

    Entry ``store[pos]`` is the global (natural) column stored at contiguous
    position ``pos``. Requires ``n % (nb * P) == 0``.
    """
    if n % (nb * n_devices) != 0:
        raise ValueError(
            f"cyclic layout needs n divisible by nb*P = {nb * n_devices}, got n={n}"
        )
    j = np.arange(n)
    blk = j // nb
    device = blk % n_devices
    local = (blk // n_devices) * nb + j % nb
    pos = device * (n // n_devices) + local
    store = np.empty(n, dtype=np.int64)
    store[pos] = j
    return store


def natural_store_positions(n: int, n_devices: int, nb: int) -> np.ndarray:
    """Inverse of :func:`cyclic_store_columns`: position of natural column j."""
    store = cyclic_store_columns(n, n_devices, nb)
    pos = np.empty(n, dtype=np.int64)
    pos[store] = np.arange(n)
    return pos


def area_balanced_splits(n_devices: int, n: int) -> list[ColumnBlock]:
    """The reference's uneven, area-balancing split (test/runtests.jl:36-38).

    ``splits(np, N, p) = round(N * (1 - sqrt((np - p) / np)))`` gives later
    blocks fewer columns, equalizing per-worker trailing-update *area* in the
    right-looking factorization. Kept as a semantic oracle; the TPU engines
    use even blocks (+ cyclic permutation) instead, since XLA shardings are
    even by construction.
    """
    def split(p: int) -> int:
        return round(n * (1.0 - math.sqrt((n_devices - p) / n_devices)))

    blocks = []
    for p in range(1, n_devices + 1):
        lo = max(1, split(p - 1) + 1)  # 1-based, as in lorange (runtests.jl:37)
        hi = min(n, split(p))          # hirange (runtests.jl:38)
        blocks.append(ColumnBlock(lo - 1, hi))  # half-open 0-based
    return blocks
