"""Shared env-scrub recipe for processes that must bypass the axon TPU tunnel.

The host pins every interpreter to the axon TPU plugin via a sitecustomize
hook on PYTHONPATH; when the relay is wedged, any backend touch can hang.
Children that must run on CPU (bench fallback, multi-chip dry run) get an
environment with the hook's triggers removed. Kept jax-free so supervisors
can import it without initializing any backend.
"""

from __future__ import annotations

import os

_REPO = os.path.dirname(os.path.abspath(__file__))


def scrubbed_cpu_env(n_devices: int | None = None, **extra: str) -> dict:
    """Env for a child pinned to the CPU platform, axon hook removed.

    ``n_devices`` forces an n-device virtual CPU platform
    (``--xla_force_host_platform_device_count``); any stale force flag in the
    inherited XLA_FLAGS is dropped either way.
    """
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO          # dhqr_tpu importable; axon_site dropped
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    ]
    if n_devices is not None:
        flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    env.update(extra)
    return env
