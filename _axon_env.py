"""Shared env-scrub recipe for processes that must bypass the axon TPU tunnel.

The host pins every interpreter to the axon TPU plugin via a sitecustomize
hook on PYTHONPATH; when the relay is wedged, any backend touch can hang.
Children that must run on CPU (bench fallback, multi-chip dry run) get an
environment with the hook's triggers removed. Kept jax-free so supervisors
can import it without initializing any backend.
"""

from __future__ import annotations

import os

_REPO = os.path.dirname(os.path.abspath(__file__))


def default_to_virtual_cpu(n_devices: int = 8,
                           optin_env: str = "DHQR_BENCH_TPU") -> bool:
    """Default THIS process to an n-device virtual CPU platform, unless
    the operator explicitly opted into hardware.

    Opt-in = ``optin_env=1`` or a JAX_PLATFORMS value naming ``tpu``
    (harness semantics — an EXPLICIT tpu request is honored; the ambient
    axon pin is ``JAX_PLATFORMS=axon`` and does not match). Without
    opt-in, sets JAX_PLATFORMS=cpu and the virtual device count so a
    wedged relay can never hang the script at first backend touch. Call
    BEFORE importing jax; afterwards the caller's
    ``cpu_requested()/force_cpu_platform()`` pair makes the choice stick
    against sitecustomize pins. Returns True when the virtual mesh was
    forced (callers use this to keep single-host problem-size defaults).

    One definition for every benchmark entry point (run.py, scaling.py,
    the ladder sweep); ``dhqr_tpu/harness.py`` keeps its own variant
    because its device count is a CLI positional.
    """
    plat = os.environ.get("JAX_PLATFORMS", "").lower()
    if os.environ.get(optin_env) == "1" or "tpu" in plat:
        return False
    if plat and plat != "cpu" and "axon" not in plat:
        # An EXPLICIT non-axon platform choice (e.g. JAX_PLATFORMS=cuda)
        # is the operator's, not the ambient pin's — honor it untouched.
        # Only the unset/cpu/axon-pin cases fall through to the virtual
        # CPU default (ADVICE r4: a setdefault-style overwrite here was
        # silently stomping explicit choices).
        return False
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        # dhqr: ignore[DHQR003] this module IS the process-bring-up env shim (pre-first-backend-use, entry points only)
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()
    # dhqr: ignore[DHQR003] same bring-up shim: pin the platform before jax initializes
    os.environ["JAX_PLATFORMS"] = "cpu"
    return True


def scrubbed_cpu_env(n_devices: int | None = None, **extra: str) -> dict:
    """Env for a child pinned to the CPU platform, axon hook removed.

    ``n_devices`` forces an n-device virtual CPU platform
    (``--xla_force_host_platform_device_count``); any stale force flag in the
    inherited XLA_FLAGS is dropped either way.
    """
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO          # dhqr_tpu importable; axon_site dropped
    env["PALLAS_AXON_POOL_IPS"] = ""
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    ]
    if n_devices is not None:
        flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    env.update(extra)
    return env
