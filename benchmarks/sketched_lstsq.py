"""dhqr-sketch decision grid: sketched vs direct lstsq + the update stream.

The round-17 decision artifact (benchmarks/README "Round-17 decision
rules"): on a tall-skinny CPU grid (every cell at the autotuner's
admission aspect, m/n >= 64),

1. **engine A/B per cell** — time the best DIRECT engine (blocked
   householder, cholqr2, tsqr — each warm, min over repeats) against
   the sketched engine, and gate BOTH answers with the tune search's
   own accuracy gate (``tune.search._verify`` — the reference
   8x-LAPACK normal-equations criterion), so admissibility is decided
   by the measurement machinery, not a hand flag. A cell whose sketch
   answer fails the gate retries with +6 CGLS iterations; a cell that
   still fails is recorded TYPED-REFUSED and excluded from the
   speedup geomean — 0 silent garbage, per the ISSUE-13 bar.
2. **warm serving** — prewarm the serve tier's "sketch" kind, dispatch
   a live mix, and pin the repeat to ZERO recompiles; then re-run the
   warm pass with request tracing ARMED and emit the
   ``armed_over_disarmed`` throughput ratio (the obs-discipline bar
   every observability layer holds).
3. **update stream** — 64 rank-1 updates against a live
   :class:`~dhqr_tpu.solvers.update.UpdatableQR`, a solve within the
   8x criterion at EVERY step, and the amortized per-update cost
   measured against a fresh factorization of the same matrix.

Ends with a ``sketched_lstsq_verdict`` row (geomean >= 2x bar, no
silent garbage, zero recompiles, update-stream flags) that the regress
gate (`python -m dhqr_tpu.obs regress`) enforces from then on.

Usage:  python benchmarks/sketched_lstsq.py [--stream-only]
Writes: benchmarks/results/sketched_lstsq_<platform>.jsonl (append)

``--stream-only`` (round 18) re-runs ONLY the 64-step update-stream
cell — the vehicle for re-measuring the Givens-based incremental R
refresh (``update-givens-floor`` regress rule) without re-rolling the
sketch A/B grid whose cross-round floors compare against the committed
round-17 cells.
"""

from __future__ import annotations

import json
import math
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# Grid: (label, m, n) — every cell at m/n >= 64 (the SketchConfig
# admission aspect), spanning n = 64..384 and aspects 64..258. Ragged m
# routes the auto operator to countsketch; the one power-of-two m cell
# exercises the SRHT path, so both operator families ship measured.
SHAPES = [
    ("tall258", 16500, 64),
    ("tall64", 8250, 128),
    ("tall128_srht", 16384, 128),
    ("tall65", 12500, 192),
    ("tall64", 16500, 256),
    ("tall65", 25000, 384),
]

DIRECT_ENGINES = ("householder", "cholqr2", "tsqr")
REPEATS = 3


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main(stream_only: bool = False) -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    rnd = int(os.environ.get("DHQR_ROUND", "18" if stream_only else "17"))
    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np

    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(_REPO, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

    from bench import SCHEMA_VERSION, _Watchdog

    from dhqr_tpu.models.qr_model import lstsq, qr
    from dhqr_tpu.solvers import UpdatableQR, sketched_lstsq
    from dhqr_tpu.solvers.sketch import resolve_operator, sketch_dim
    from dhqr_tpu.tune.search import _verify
    from dhqr_tpu.utils.config import SketchConfig
    from dhqr_tpu.utils.profiling import sync
    from dhqr_tpu.utils.testing import (
        TOLERANCE_FACTOR,
        normal_equations_residual,
        oracle_residual,
    )

    _stage("backend_init")
    with _Watchdog("backend_init", 240):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    out_path = os.path.join(_REPO, "benchmarks", "results",
                            f"sketched_lstsq_{platform}.jsonl")
    skcfg = SketchConfig.from_env()

    def emit(rec):
        rec.update(platform=platform, device_kind=kind, round=rnd,
                   schema_version=SCHEMA_VERSION)
        line = json.dumps(rec)
        print(line, flush=True)
        with open(out_path, "a") as f:
            f.write(line + "\n")

    def timed(fn, *args, **kw):
        """(min warm seconds over REPEATS, last output)."""
        out = fn(*args, **kw)
        sync(out)
        best = float("inf")
        for _ in range(REPEATS):
            t0 = time.perf_counter()
            out = fn(*args, **kw)
            sync(out)
            best = min(best, time.perf_counter() - t0)
        return best, out

    rng = np.random.default_rng(0)

    # Update stream: 64 rank-1 steps, gated per step, amortized cost vs
    # a fresh factorization. Round 18: the rank-1 refresh is the O(n^2)
    # Givens/hyperbolic sweep pair (solvers/update) — the row stamps
    # ``refresh`` so the regress gate can pin the improved floor
    # (``update-givens-floor``) without re-litigating the round-17
    # re-Cholesky rows, and additionally times the UPDATE step alone
    # (solve excluded) — the number the refresh actually moved.
    def update_stream_cell():
        _stage("update_stream")
        mu, nu = 4096, 64
        Au = jnp.asarray(rng.random((mu, nu)), jnp.float32)
        bu = jnp.asarray(rng.random(mu), jnp.float32)
        fresh_s, _ = timed(lambda: qr(Au))
        fact = UpdatableQR(Au)
        fact.update(jnp.asarray(rng.standard_normal(mu).astype(np.float32)),
                    jnp.asarray(rng.standard_normal(nu).astype(np.float32)))
        fact.solve(bu)                  # warm both programs
        step_secs = []
        upd_secs = []
        stream_worst = 0.0
        stream_ok = True
        for _ in range(64):
            u = jnp.asarray(
                (0.1 * rng.standard_normal(mu)).astype(np.float32))
            v = jnp.asarray(
                (0.1 * rng.standard_normal(nu)).astype(np.float32))
            t0 = time.perf_counter()
            fact.update(u, v)
            sync(fact.r_matrix())
            t1 = time.perf_counter()
            upd_secs.append(t1 - t0)
            x = fact.solve(bu)
            sync(x)
            step_secs.append(time.perf_counter() - t0)
            live = np.asarray(fact.matrix)
            ratio = normal_equations_residual(live, np.asarray(x), bu) \
                / oracle_residual(live, np.asarray(bu))
            stream_worst = max(stream_worst, ratio)
            stream_ok = stream_ok and ratio < TOLERANCE_FACTOR
        step_secs.sort()
        upd_secs.sort()
        per_update = step_secs[len(step_secs) // 2]
        upd_only = upd_secs[len(upd_secs) // 2]
        emit({
            "metric": "updatable_qr_stream",
            "steps": 64,
            "value": round(per_update / fresh_s, 4),
            "unit": "median (update+solve) s / fresh factorization s",
            "refresh": "givens",
            "per_update_s": round(per_update, 6),
            "update_only_s": round(upd_only, 6),
            "update_only_over_fresh": round(upd_only / fresh_s, 4),
            "fresh_factor_s": round(fresh_s, 6),
            "worst_ratio_vs_lapack": round(stream_worst, 4),
            "residual_criterion": TOLERANCE_FACTOR,
            "refactors": fact.refactor_count,
            "every_step_within_8x": stream_ok,
        })

        # n-heavy twin (round 18): at 4096x64 the step is Gram-matvec
        # bound and the refresh choice barely shows; at 2048x512 the
        # old n^3/3 re-Cholesky IS the step (44.7 MF vs the 4.2 MF
        # matvec pair), so this cell times the Givens sweep against a
        # directly-measured re-Cholesky of the SAME live Gram — the
        # comparator the ``update-givens-floor`` regress rule pins.
        _stage("update_stream_nheavy")
        mh, nh, steps_h = 2048, 512, 16
        Ah = jnp.asarray(rng.random((mh, nh)), jnp.float32)
        bh = jnp.asarray(rng.random(mh), jnp.float32)
        fact_h = UpdatableQR(Ah)
        uh = jnp.asarray((0.1 * rng.standard_normal(mh)).astype(np.float32))
        vh = jnp.asarray((0.1 * rng.standard_normal(nh)).astype(np.float32))
        fact_h.update(uh, vh)
        fact_h.solve(bh)            # warm programs
        from dhqr_tpu.numeric.guards import checked_cholesky
        sync(checked_cholesky(fact_h._G))  # warm the comparator
        upd_h, chol_h = [], []
        ok_h = True
        for _ in range(steps_h):
            u = jnp.asarray(
                (0.1 * rng.standard_normal(mh)).astype(np.float32))
            v = jnp.asarray(
                (0.1 * rng.standard_normal(nh)).astype(np.float32))
            t0 = time.perf_counter()
            fact_h.update(u, v)
            sync(fact_h.r_matrix())
            upd_h.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            sync(checked_cholesky(fact_h._G))   # the round-17 refresh
            chol_h.append(time.perf_counter() - t0)
            x = fact_h.solve(bh)
            live = np.asarray(fact_h.matrix)
            ratio = normal_equations_residual(live, np.asarray(x), bh) \
                / oracle_residual(live, np.asarray(bh))
            ok_h = ok_h and ratio < TOLERANCE_FACTOR
        upd_h.sort()
        chol_h.sort()
        med_upd = upd_h[len(upd_h) // 2]
        med_chol = chol_h[len(chol_h) // 2]
        fresh_h, _ = timed(lambda: qr(Ah))
        emit({
            "metric": "updatable_qr_stream_nheavy",
            "steps": steps_h,
            "m": mh, "n": nh,
            "value": round(med_upd / (med_upd + med_chol), 4),
            "unit": "givens step s / re-Cholesky-era step s (>= upper "
                    "bound on the true ratio: the denominator still "
                    "contains the sweeps)",
            "refresh": "givens",
            "update_only_s": round(med_upd, 6),
            "recholesky_refresh_s": round(med_chol, 6),
            "update_over_fresh": round(med_upd / fresh_h, 4),
            "fresh_factor_s": round(fresh_h, 6),
            "every_step_within_8x": ok_h,
            "refactors": fact_h.refactor_count,
        })
        return per_update, fresh_s, stream_ok


    if stream_only:
        update_stream_cell()
        _stage("done")
        return

    speedups = []
    refused = 0
    worst_gate = 0.0
    for label, m, n in SHAPES:
        _stage(f"cell_{m}x{n}")
        A = jnp.asarray(rng.random((m, n)), jnp.float32)
        b = jnp.asarray(rng.random(m), jnp.float32)
        args = (A, b)
        with _Watchdog(f"cell_{m}x{n}", 300):
            best_direct, best_engine = float("inf"), None
            for eng in DIRECT_ENGINES:
                try:
                    secs, out = timed(lstsq, A, b, engine=eng)
                except Exception:
                    continue        # engine rejects the shape: skip
                ok, _ = _verify("lstsq", out, args, None)
                if ok and secs < best_direct:
                    best_direct, best_engine = secs, eng
            refine = None           # SketchConfig baseline first
            sk_secs, out = timed(sketched_lstsq, A, b, refine=refine)
            ok, err = _verify("lstsq", out, args, None)
            if not ok:
                # The ISSUE-13 ladder: buy the gate back with more CGLS
                # iterations before refusing.
                refine = skcfg.refine + 6
                sk_secs, out = timed(sketched_lstsq, A, b, refine=refine)
                ok, err = _verify("lstsq", out, args, None)
        # A cell with NO gate-passing direct baseline cannot claim a
        # speedup (an inf ratio would poison the geomean into a vacuous
        # pass, and float('inf') is not even valid JSON): such a cell
        # is excluded from the geomean and flagged, never silently won.
        no_baseline = best_engine is None
        cell_refused = not ok
        refused += cell_refused
        if not cell_refused and not no_baseline:
            speedups.append(best_direct / sk_secs)
        if not cell_refused:
            worst_gate = max(worst_gate, err)
        cell_value = (round(best_direct / sk_secs, 4)
                      if ok and not no_baseline else None)
        emit({
            "metric": f"sketched_lstsq_{m}x{n}",
            "regime": label,
            "value": cell_value,
            "unit": "x requests/s vs best direct engine",
            "no_direct_baseline": no_baseline,
            "sketch_s": round(sk_secs, 6),
            "direct_s": (round(best_direct, 6)
                         if not no_baseline else None),
            "requests_per_s_sketch": round(1.0 / sk_secs, 2),
            "requests_per_s_direct": (round(1.0 / best_direct, 2)
                                      if not no_baseline else None),
            "best_direct_engine": best_engine,
            "operator": resolve_operator(skcfg.operator, m),
            "sketch_rows": sketch_dim(m, n, factor=skcfg.factor),
            "cgls_iters": refine if refine is not None else skcfg.refine,
            "residual_ratio_vs_lapack": round(err, 4),
            "residual_criterion": TOLERANCE_FACTOR,
            "gate": "tune.search._verify",
            "typed_refused": cell_refused,
        })

    # Warm serving of the new kind: prewarm -> dispatch -> 0-recompile
    # repeat, disarmed vs obs-armed throughput.
    _stage("serve_warm")
    from dhqr_tpu import obs as obs_mod
    from dhqr_tpu.serve import batched_sketched_lstsq, prewarm
    from dhqr_tpu.serve.cache import ExecutableCache
    from dhqr_tpu.utils.config import ObsConfig

    cache = ExecutableCache(max_size=32)
    mix = [(4096, 64)] * 4 + [(2048, 32)] * 8
    prewarm([(4, 4096, 64), (8, 2048, 32)], kind="sketch", cache=cache)
    warm_misses = cache.stats()["misses"]
    As = [jnp.asarray(rng.random(s), jnp.float32) for s in mix]
    bs = [jnp.asarray(rng.random(s[0]), jnp.float32) for s in mix]

    def serve_pass():
        return batched_sketched_lstsq(As, bs, cache=cache)

    # Armed-vs-disarmed by ALTERNATING interleaved passes, medians
    # compared (the serving_obs.py discipline): two sequential min-of-N
    # windows alias container contention into the ratio — measured a
    # spurious 0.87 on a quiet change — while interleaving puts both
    # arms under the same noise.
    xs = serve_pass()           # settle/compile
    sync(xs)
    for A, x, b in zip(As, xs, bs):
        res = normal_equations_residual(A, np.asarray(x), b)
        assert res < TOLERANCE_FACTOR * oracle_residual(
            np.asarray(A), np.asarray(b)), "serve residual over the bar"
    ocfg = ObsConfig(enabled=True, buffer_spans=8192)
    dis_samples, arm_samples = [], []
    try:
        obs_mod.arm(ocfg)
        sync(serve_pass())      # settle the armed arm too
        obs_mod.disarm()
        for _ in range(5):
            t0 = time.perf_counter()
            sync(serve_pass())
            dis_samples.append(time.perf_counter() - t0)
            obs_mod.arm(ocfg)
            t0 = time.perf_counter()
            sync(serve_pass())
            arm_samples.append(time.perf_counter() - t0)
            obs_mod.disarm()
    finally:
        obs_mod.disarm()
    dis_samples.sort()
    arm_samples.sort()
    disarmed_s = dis_samples[len(dis_samples) // 2]
    armed_s = arm_samples[len(arm_samples) // 2]
    serve_recompiles = cache.stats()["misses"] - warm_misses
    armed_ratio = disarmed_s / armed_s
    emit({
        "metric": "sketched_lstsq_serve",
        "phase": "warm_armed",
        "value": round(len(mix) / disarmed_s, 2),
        "unit": "requests/s (disarmed warm pass)",
        "requests": len(mix),
        "armed_over_disarmed": round(armed_ratio, 4),
        "recompiles_after_prewarm": serve_recompiles,
    })

    per_update, fresh_s, stream_ok = update_stream_cell()

    geomean = math.exp(sum(math.log(s) for s in speedups)
                       / max(1, len(speedups))) if speedups else 0.0
    update_amortized = per_update / fresh_s
    ok = (geomean >= 2.0 and refused == 0 and serve_recompiles == 0
          and len(speedups) == len(SHAPES)    # every cell measured A/B
          and armed_ratio >= 0.95 and stream_ok
          and update_amortized < 1.0)
    emit({
        "metric": "sketched_lstsq_verdict",
        "kind": "verdict",
        "value": round(geomean, 4),
        "unit": "geomean x requests/s vs best direct engine",
        "cells": len(SHAPES),
        "cells_in_geomean": len(speedups),
        "typed_refused_cells": refused,
        "geomean_meets_2x": geomean >= 2.0,
        "worst_gate_ratio": round(worst_gate, 4),
        "no_silent_garbage": True,      # gated or typed-refused per cell
        "serve_recompiles_after_prewarm": serve_recompiles,
        "armed_over_disarmed": round(armed_ratio, 4),
        "update_stream_within_8x": stream_ok,
        "update_over_fresh": round(update_amortized, 4),
        "ok": bool(ok),
    })
    _stage("done")


if __name__ == "__main__":
    main(stream_only="--stream-only" in sys.argv[1:])
