"""Round-3 TPU probe: scale ladder + rectangular + complex64 hardware data.

Stages (each one JSONL line, watchdogged, largest-value-first):

1. ``qr_12288`` at nb=256 and nb=512 — refines the auto-width crossover
   (measured: 256 wins at 8192, 512 wins at 16384; where between?).
2. ``qr_32768x4096`` nb=256 — the BASELINE.md config-4 SHAPE (blocked
   compact-WY rectangular) on one chip. Device time ~0.1 s, chain=5.
3. ``qr_c64_4096`` — first hardware datum for the complex64 engine with
   the planar-arithmetic Pallas panel kernel (the TPU analogue of the
   reference's ACTIVE hand-SIMD ComplexF64 hotloop, reference
   src/DistributedHouseholderQR.jl:174-196). Complex flop model:
   a complex MAC is 4 real multiplies + 4 adds, so dense complex QR
   costs ~4x the real count: flops = 4 * (2mn^2 - (2/3)n^3).
4. ``qr_32768`` nb=256 — the largest square that fits comfortably
   (4.3 GB + workspace in 16 GB HBM); device time ~3-4 s, single
   dispatch timing (RTT is noise at that scale).

Run ONE instance at a time (the axon relay allows a single TPU process).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


# Gate override: Mosaic's allocator is the arbiter during this probe (the
# per-kind table stays at the last VALIDATED budget; if the 67 MB panel
# below compiles and wins, the table gets raised with the new datum).
os.environ.setdefault("DHQR_PALLAS_VMEM_BYTES", str(100 * 1024 * 1024))
os.environ.setdefault("DHQR_PALLAS_PANEL_COPIES", "1")


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main() -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    from bench import _Watchdog

    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from dhqr_tpu.ops.blocked import _blocked_qr_impl
    from dhqr_tpu.utils.profiling import sync

    _stage("backend_init")
    with _Watchdog("backend_init", 150):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    rng = np.random.default_rng(0)

    def emit(rec):
        rec["platform"] = platform
        rec["device_kind"] = kind
        print(json.dumps(rec), flush=True)

    def chain_time(m, n, nb, chain, watchdog, dtype="f32", repeats=3):
        name = f"qr_{dtype}_{m}x{n}_nb{nb}"
        _stage(name)
        try:
            with _Watchdog(name, watchdog):
                if dtype == "c64":
                    A = jnp.asarray(rng.random((m, n)) +
                                    1j * rng.random((m, n)), jnp.complex64)
                    flops = 4.0 * (2.0 * m * n * n - (2.0 / 3.0) * n**3)
                else:
                    A = jnp.asarray(rng.random((m, n)), jnp.float32)
                    flops = 2.0 * m * n * n - (2.0 / 3.0) * n**3
                sync(A)
                kw = dict(precision="highest", pallas=True, norm="fast",
                          panel_impl="loop")
                t0 = time.perf_counter()
                single = _blocked_qr_impl.lower(A, nb, **kw).compile()
                H, al = single(A)
                sync(al)
                compile_s = time.perf_counter() - t0

                def tmin(f):
                    ts = []
                    for _ in range(repeats):
                        t0 = time.perf_counter()
                        r = f(A)
                        sync(r[1])
                        ts.append(time.perf_counter() - t0)
                    return min(ts)

                t1 = tmin(lambda A: single(A))
                rec = {"metric": f"qr_gflops_per_chip_{dtype}_{m}x{n}",
                       "unit": "GFLOP/s", "block_size": nb,
                       "pallas_panels": True,
                       "seconds_single_dispatch": round(t1, 4),
                       "compile_seconds": round(compile_s, 2)}
                if chain and chain > 1:
                    def chained(A):
                        def body(C, _):
                            Hc, ac = _blocked_qr_impl(C, nb, **kw)
                            return Hc, ac[0]
                        return lax.scan(body, A, None, length=chain)
                    ck = jax.jit(chained).lower(A).compile()
                    Hc, s = ck(A)
                    sync(s)
                    tk = tmin(lambda A: (None, ck(A)[1]))
                    t = (tk - t1) / (chain - 1)
                    unreliable = not (tk > t1 * 1.05 and t > 0)
                    if unreliable:
                        t = t1
                    rec.update(seconds_chain=round(tk, 4), chain_length=chain,
                               chain_unreliable=unreliable)
                else:
                    t = t1  # device time >> RTT at this scale
                rec["seconds"] = round(t, 4)
                rec["value"] = round(flops / t / 1e9, 2)
                if dtype == "c64":
                    rec["flop_model"] = "4*(2mn^2-(2/3)n^3) complex-as-real"
                emit(rec)
        except Exception as ex:
            emit({"metric": name, "ok": False,
                  "error": f"{type(ex).__name__}: {ex}"[:500]})

    # 0. VMEM frontier: does a 67 MB single-copy panel fit? (v5e datasheet
    # VMEM is far above the 34 MB validated so far; Mosaic decides.)
    big_panel_ok = False
    _stage("panel_32768x512")
    try:
        with _Watchdog("panel_32768x512", 240):
            from dhqr_tpu.ops.pallas_panel import _panel_qr_pallas_jit

            panel = jnp.asarray(rng.standard_normal((32768, 512)),
                                jnp.float32)
            sync(panel)
            comp = _panel_qr_pallas_jit.lower(
                panel, 0, interpret=False).compile()
            pf, al = comp(panel, 0)
            sync(al)
            vdev = float(jnp.max(jnp.abs(
                jnp.sum(jnp.tril(pf) * jnp.tril(pf), axis=0) - 2.0)))
            big_panel_ok = vdev < 1e-4 and bool(jnp.all(jnp.isfinite(al)))
            emit({"metric": "panel_32768x512", "ok": big_panel_ok,
                  "max_vnorm_dev": vdev})
    except Exception as ex:
        emit({"metric": "panel_32768x512", "ok": False,
              "error": f"{type(ex).__name__}: {ex}"[:500]})

    # 1. crossover refinement
    chain_time(12288, 12288, 256, 3, 420)
    chain_time(12288, 12288, 512, 3, 420)
    # 2. BASELINE config-4 shape (rectangular compact-WY)
    chain_time(32768, 4096, 256, 5, 480)
    # 3. complex64 datum (planar Pallas panels active)
    chain_time(4096, 4096, 256, 9, 420, dtype="c64")
    # 4. largest square (single dispatch; device time >> RTT)
    chain_time(32768, 32768, 256, 0, 560, repeats=2)
    if big_panel_ok:
        chain_time(32768, 32768, 512, 0, 560, repeats=2)
    _stage("done")


if __name__ == "__main__":
    main()
