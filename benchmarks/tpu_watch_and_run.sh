#!/bin/bash
# Watch the relay; the moment it answers, run the round-4 hardware session
# sized to the time remaining before the driver's round-end bench window.
# ONE TPU process at a time: while this runs, nothing else may touch the TPU.
#   bash benchmarks/tpu_watch_and_run.sh [deadline_HH:MM]
#
# The deadline (default 22:45 UTC) is when the TPU must be FREE again so
# the driver's own round-end bench.py run cannot collide with a session
# still in flight (a collision can wedge the relay for both). Stage tiers
# by time remaining at recovery, headline first — sized to the session's
# WIDENED bench window (DHQR_BENCH_TPU_TIMEOUT=1500 in tpu_session_r4.sh:
# the bench stage alone can hold the TPU ~28 min):
#   >= 180 min : bench agg split lookahead trailing phase cembed  (everything)
#   >=  90 min : bench agg split cembed
#   >=  30 min : bench
#   <   30 min : give up (leave the window to the driver)
set -u
cd "$(dirname "$0")/.."
# One round tag for the whole chain (watcher -> session -> bench.py ->
# analyze_r4.py): export so every child stamps/filters the same round.
export DHQR_ROUND="${DHQR_ROUND:-5}"
# UTC explicitly (the driver's window is UTC; a non-UTC host must not
# shift the tiering), with day rollover: a deadline time-of-day already
# past means tomorrow's. A bare NUMBER keeps the script's original
# max-wait-seconds semantics (deadline = now + N) so detached relaunches
# with the old usage still work.
now0=$(date +%s)
arg="${1:-22:45}"
if [[ "$arg" =~ ^[0-9]+$ ]]; then
  DEADLINE=$(( now0 + arg ))
else
  DEADLINE=$(date -u -d "$arg" +%s) || exit 1
  if [ "$DEADLINE" -le "$now0" ]; then
    DEADLINE=$(( DEADLINE + 86400 ))
  fi
fi
SLEEP=900              # 15 min between probes
while :; do
  now=$(date +%s)
  rem=$(( DEADLINE - now ))
  if [ "$rem" -lt 1800 ]; then
    echo "=== $(date -u +%H:%M:%S): <30 min to deadline; giving up" >&2
    exit 2
  fi
  # Outer kernel-level kill (timeout -k): the probe's internal watchdogs
  # are thread-based and can be GIL-starved when the PJRT init blocks in
  # C++ without releasing the GIL (measured round 5 — the probe outlived
  # both its 240 s watchdog and a plain SIGTERM when a handler was
  # installed). 900 s is generous enough that a healthy-but-slow first
  # compile is never killed mid-flight (the wedge risk), while a truly
  # hung probe can no longer hang the watcher loop itself.
  # Each probe outcome lands in a state file bench.py consults: a FRESH
  # "wedged" verdict lets the round-end supervised bench shorten (never
  # skip) its own TPU attempt instead of burning most of the driver's
  # window re-discovering the wedge.
  if timeout -k 30 900 python benchmarks/tpu_alive_probe.py; then
    echo "{\"ts\": $(date +%s), \"alive\": true}" \
      > benchmarks/results/relay_state.json
    now=$(date +%s); rem=$(( DEADLINE - now ))
    if   [ "$rem" -ge 10800 ]; then
      stages="bench agg reconstruct split lookahead trailing phase cembed bigsize"
    # Mid tier DELIBERATELY swaps split for reconstruct/agg: the round-5
    # levers outrank the round-3 split ladder when the window cannot fit
    # both (bench ~28 min + agg ~20 + reconstruct ~20 + cembed ~10 fills
    # the 90-min tier; split still runs in the full tier above).
    elif [ "$rem" -ge 5400 ]; then stages="bench agg reconstruct cembed"
    elif [ "$rem" -ge 1800 ]; then
      stages="bench"
      # Late recovery: size the bench child to the time left (minus the
      # CPU fallback + exit margin) so it cannot overrun the deadline
      # into the driver's own TPU window.
      export DHQR_BENCH_TPU_TIMEOUT=$(( rem - 900 ))
    else
      echo "=== relay recovered with only $rem s left; leaving the window" >&2
      exit 2
    fi
    echo "=== relay alive at $(date -u +%H:%M:%S), $rem s to deadline;" \
         "running: $stages" >&2
    exec bash benchmarks/tpu_session_r4.sh $stages
  fi
  echo "{\"ts\": $(date +%s), \"alive\": false}" \
    > benchmarks/results/relay_state.json
  echo "=== relay still wedged at $(date -u +%H:%M:%S); sleeping $SLEEP s" >&2
  sleep "$SLEEP"
done
