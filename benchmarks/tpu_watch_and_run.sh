#!/bin/bash
# Watch the relay; the moment it answers, run the round-4 hardware session.
# ONE TPU process at a time: while this runs, nothing else may touch the TPU.
#   bash benchmarks/tpu_watch_and_run.sh [max_wait_seconds]
set -u
cd "$(dirname "$0")/.."
MAX_WAIT=${1:-21600}   # give up after 6 h by default
SLEEP=900              # 15 min between probes
start=$(date +%s)
while :; do
  if python benchmarks/tpu_alive_probe.py; then
    echo "=== relay alive at $(date -u +%H:%M:%S); starting session" >&2
    # Every stage except `alive` (this loop just proved the relay is up);
    # keep this list in sync with the session script's default.
    exec bash benchmarks/tpu_session_r4.sh bench split trailing phase cembed
  fi
  now=$(date +%s)
  if [ $((now - start)) -ge "$MAX_WAIT" ]; then
    echo "=== gave up after $((now - start)) s; relay still wedged" >&2
    exit 2
  fi
  echo "=== relay still wedged at $(date -u +%H:%M:%S); sleeping $SLEEP s" >&2
  sleep "$SLEEP"
done
