"""Round-3 TPU diagnostic probe: isolate the complex64 / large-size failures.

Earlier probes found three UNIMPLEMENTED failures on the v5e relay
(tpu_r3_scale.jsonl, tpu_r3_tsqr_pallas.jsonl):

* complex64 blocked QR at 1024^2 — even on the pure-XLA path;
* float32 QR at 24576^2 and 32768^2 (2.4 / 4.3 GB buffers).

This probe bisects, smallest-first, each op the engine uses:

c64 ladder: matmul -> conj/transpose -> triangular_solve -> unblocked QR
(no triangular_solve) -> blocked QR. Whichever rung fails first names the
unimplemented primitive; if ``triangular_solve`` is the culprit the
compact-WY T-factor apply can be respelled as log2(nb) small GEMMs (the
unit-triangular doubling inverse) — worth knowing before building it.

f32 size ladder: QR at 18432^2 and 20480^2 narrows where between 16384
(works) and 24576 (fails) the backend gives up, and whether the limit is
bytes or something else.

Run ONE instance at a time (the axon relay allows a single TPU process).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main() -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    from bench import _Watchdog

    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from dhqr_tpu.utils.profiling import sync

    _stage("backend_init")
    with _Watchdog("backend_init", 150):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    rng = np.random.default_rng(0)

    def emit(rec):
        rec["platform"] = platform
        rec["device_kind"] = kind
        print(json.dumps(rec), flush=True)

    def try_stage(name, fn, watchdog=180):
        _stage(name)
        try:
            with _Watchdog(name, watchdog):
                out = fn()
                emit({"metric": name, "ok": True, **(out or {})})
                return True
        except Exception as ex:
            emit({"metric": name, "ok": False,
                  "error": f"{type(ex).__name__}: {ex}"[:300]})
            return False

    C = jnp.asarray(rng.random((256, 256)) + 1j * rng.random((256, 256)),
                    jnp.complex64)

    def c64_matmul():
        r = jnp.matmul(C, C, precision="highest")
        sync(jnp.abs(r[0, 0]))

    def c64_conj_dot():
        r = jnp.matmul(jnp.conj(C.T), C, precision="highest")
        sync(jnp.abs(r[0, 0]))

    def c64_trisolve():
        U = jnp.triu(C) + 4 * jnp.eye(256, dtype=jnp.complex64)
        r = lax.linalg.triangular_solve(U, C, left_side=True, lower=False)
        sync(jnp.abs(r[0, 0]))

    def c64_trisolve_unit_conj():
        # The exact variant apply_block_reflector_h uses.
        U = jnp.triu(C, k=1) * 0.01 + jnp.eye(256, dtype=jnp.complex64)
        r = lax.linalg.triangular_solve(
            U, C, left_side=True, lower=False, transpose_a=True,
            conjugate_a=True, unit_diagonal=True)
        sync(jnp.abs(r[0, 0]))

    def c64_unblocked_qr():
        from dhqr_tpu.ops.householder import _householder_qr_impl

        H, al = _householder_qr_impl(C, precision="highest", norm="fast")
        sync(jnp.abs(al[0]))

    def c64_blocked_qr():
        from dhqr_tpu.ops.blocked import _blocked_qr_impl

        H, al = _blocked_qr_impl(C, 64, precision="highest", pallas=False,
                                 norm="fast")
        sync(jnp.abs(al[0]))

    ok_mm = try_stage("c64_matmul_256", c64_matmul)
    try_stage("c64_conj_dot_256", c64_conj_dot)
    try_stage("c64_trisolve_256", c64_trisolve)
    try_stage("c64_trisolve_unit_conj_256", c64_trisolve_unit_conj)
    try_stage("c64_unblocked_qr_256", c64_unblocked_qr, watchdog=300)
    try_stage("c64_blocked_qr_256", c64_blocked_qr, watchdog=300)

    # f32 size ladder
    from dhqr_tpu.ops.blocked import _blocked_qr_impl

    def f32_qr(n):
        def run():
            A = jnp.asarray(rng.random((n, n)), jnp.float32)
            sync(A)
            t0 = time.perf_counter()
            H, al = _blocked_qr_impl(A, 512, precision="highest",
                                     pallas=True, norm="fast")
            sync(al)
            return {"seconds_first": round(time.perf_counter() - t0, 2)}
        return run

    try_stage("f32_qr_18432_nb512", f32_qr(18432), watchdog=560)
    try_stage("f32_qr_20480_nb512", f32_qr(20480), watchdog=560)
    _stage("done")


if __name__ == "__main__":
    main()
