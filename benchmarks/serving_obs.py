"""dhqr-obs acceptance: traced chaos + armed-tracing overhead ladder.

The round-14 tentpole's decision artifact, reusing the round-12 chaos
machinery (benchmarks/serving_faults.py: same shape ladder, prewarmed
AOT cache, seeded Poisson×Zipf open loop):

* ``warm_disarmed`` / ``warm_armed`` — the warm closed-loop serving
  throughput (repeated submit-all + drain over the prewarmed cache),
  measured disarmed and with tracing ARMED, interleaved. Acceptance:
  armed costs <= 5% requests/s (ratio of per-arm MEDIANS >= 0.95 —
  per-sample noise on this shared CPU is ±30%, far above the
  few-appends-per-request tracing cost, and a median absorbs the
  one-off stalls a best-of amplified), and the armed passes compile
  NOTHING (trace ids
  provably absent from cache keys — the same pin tests/test_obs.py
  holds as key parity);
* ``chaos_traced`` — the seeded fault schedule (``serve.dispatch`` +
  ``serve.latency``) at 0.9x capacity with tracing armed and the
  flight recorder's auto-dump pointed at a scratch dir. Acceptance:
  every accepted future resolves; every TYPED-ERROR future's trace
  reconstructs its complete path — first span ``submit``, last span
  ``resolve`` with the error's own type as outcome, a ``dispatch``
  attempt in between, and (for post-retry failures) the
  retry/isolate/bisect hop that explains WHY — and the auto-dump file
  carries the same paths for ``python -m dhqr_tpu.obs dump``;
* ``typed_path`` — the deterministic twin of the chaos check (light
  chaos can recover EVERY request via retry, leaving nothing typed to
  inspect): an unbounded ``serve.dispatch`` schedule against four
  lone requests forces the full escalation — submit → flush →
  dispatch → retry (cause) → isolate → resolve typed — so the
  complete-path acceptance always has deterministic evidence;
* the ``chaos_traced`` record embeds the unified registry snapshot
  (``dhqr_tpu.obs.registry``) taken while the scheduler, the armed
  fault harness and the trace recorder are all LIVE, so the artifact
  itself demonstrates the full dotted-name surface
  (``serve.sched.*``/``serve.cache.*``/``faults.*``/``numeric.*``/
  ``obs.*``) the bench summary now stamps.

Usage:  python benchmarks/serving_obs.py [n_requests] [rate_frac]
Writes: benchmarks/results/serving_obs_<platform>.jsonl (append).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# The round-8/11/12 shape ladder verbatim — numbers stay comparable to
# the serving_async / serving_faults artifacts.
SHAPE_LADDER = [
    (64, 16), (100, 36), (128, 48), (192, 64),
    (250, 100), (384, 128), (500, 180), (640, 256),
]
MICRO_BATCH = 32
SLO_MS = 2000.0
FLUSH_INTERVAL_MS = 100.0
WARM_REPEATS = 5          # median-of per arm: a single one-off stall
                          # (GC pause, thread-pool start) cannot move a
                          # median the way it moved a best-of-3 sample
LIGHT_FAULTS = dict(dispatch_p=0.15, latency_p=0.40, latency_ms=40.0)


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main(n_requests: int = 384, rate_frac: float = 0.90) -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    from bench import SCHEMA_VERSION, ROUND, _Watchdog

    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np

    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(_REPO, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

    from dhqr_tpu import faults, obs
    from dhqr_tpu.obs import ObsConfig
    from dhqr_tpu.serve import AsyncScheduler, ServeError, prewarm
    from dhqr_tpu.serve.cache import ExecutableCache
    from dhqr_tpu.utils.config import (FaultConfig, SchedulerConfig,
                                       ServeConfig)
    from dhqr_tpu.utils.profiling import LatencyHistogram, sync

    _stage("backend_init")
    with _Watchdog("backend_init", 240):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    out_path = os.path.join(_REPO, "benchmarks", "results",
                            f"serving_obs_{platform}.jsonl")

    def emit(rec):
        rec.update(platform=platform, device_kind=kind, round=ROUND,
                   schema_version=SCHEMA_VERSION)
        line = json.dumps(rec)
        print(line, flush=True)
        with open(out_path, "a") as f:
            f.write(line + "\n")

    # ---- the request stream (fixed seeds: artifact is reproducible) ----
    rng = np.random.default_rng(0)
    ranks = np.arange(len(SHAPE_LADDER))
    weights = 1.0 / (ranks + 1.0) ** 1.1
    weights /= weights.sum()
    picks = rng.choice(len(SHAPE_LADDER), size=n_requests, p=weights)
    shapes = [SHAPE_LADDER[i] for i in picks]
    As = [jnp.asarray(rng.random(s), jnp.float32) for s in shapes]
    bs = [jnp.asarray(rng.random(s[0]), jnp.float32) for s in shapes]
    sync(As[-1])
    scfg = ServeConfig(max_batch=MICRO_BATCH)

    _stage("prewarm")
    with _Watchdog("prewarm", 2400):
        acache = ExecutableCache(max_size=64)
        pow2 = [1 << i for i in range((MICRO_BATCH - 1).bit_length() + 1)
                if 1 << i <= MICRO_BATCH]
        keys = prewarm([(c, m, n) for (m, n) in SHAPE_LADDER for c in pow2],
                       serve_config=scfg, cache=acache)
    emit({"metric": "serving_obs", "phase": "prewarm",
          "keys": len(keys), "cache": acache.stats()})

    # ---- warm closed-loop throughput, disarmed vs armed ----------------
    def warm_drain_rps() -> float:
        """One closed-loop measurement: submit the whole stream, drain,
        twice; requests/s over the drains (the round-11 sync-ceiling
        shape). MANUAL mode (``start=False`` — drain polls inline, no
        dispatcher threads) on purpose: this phase measures the
        INSTRUMENTATION delta, a few ring-buffer appends per request,
        and threaded drains carry ±30% per-sample scheduling jitter
        that would drown it (measured: manual-mode samples sit within
        ±5%, threaded within ±30% on this CPU). Absolute threaded
        capacity stays the round-11/12 artifacts' job — the chaos
        phase below still runs the live dispatcher pool."""
        sched = AsyncScheduler(
            serve_config=scfg,
            sched_config=SchedulerConfig(slo_ms=60e3, queue_depth=16384,
                                         flush_interval_ms=FLUSH_INTERVAL_MS),
            cache=acache, start=False)
        drain_s = 0.0
        for _ in range(2):
            futs = [sched.submit("lstsq", A, b, deadline=60.0)
                    for A, b in zip(As, bs)]
            t0 = time.perf_counter()
            sched.drain()
            drain_s += time.perf_counter() - t0
            assert all(f.exception() is None for f in futs)
        sched.shutdown()
        return 2 * n_requests / drain_s

    _stage("warm_ladder")
    with _Watchdog("warm_ladder", 2400):
        warm_drain_rps()                      # untimed warm-up passes:
        warm_drain_rps()                      # the minutes of prewarm
        # compiles above leave the container in a transiently throttled
        # state, and the first timed samples after it read low — two
        # full settle passes keep that drift out of BOTH arms.
        disarmed, armed = [], []
        misses_before_armed = None

        def one_armed_sample() -> float:
            nonlocal misses_before_armed
            with obs.observed(ObsConfig(enabled=True,
                                        buffer_spans=65536)) as rec:
                if misses_before_armed is None:
                    misses_before_armed = acache.stats()["misses"]
                rps = warm_drain_rps()
                one_armed_sample.spans = rec.stats()
            return rps

        for rep in range(WARM_REPEATS):
            # Interleaved A/B with ALTERNATING order: any slow
            # monotone drift (throttle recovery, cache settling) lands
            # on each arm's first-and-second slots equally, so the
            # medians compare like with like.
            if rep % 2 == 0:
                disarmed.append(warm_drain_rps())
                armed.append(one_armed_sample())
            else:
                armed.append(one_armed_sample())
                disarmed.append(warm_drain_rps())
        armed_spans = one_armed_sample.spans
        armed_recompiles = acache.stats()["misses"] - misses_before_armed
        import statistics

        overhead_ratio = statistics.median(armed) / statistics.median(
            disarmed)
    emit({"metric": "serving_obs", "phase": "warm_disarmed",
          "requests_per_s": [round(r, 1) for r in disarmed],
          "median_rps": round(statistics.median(disarmed), 1)})
    emit({"metric": "serving_obs", "phase": "warm_armed",
          "requests_per_s": [round(r, 1) for r in armed],
          "median_rps": round(statistics.median(armed), 1),
          "armed_over_disarmed": round(overhead_ratio, 4),
          "recompiles_armed": armed_recompiles,
          "recorder": armed_spans})

    # ---- traced chaos: open loop under the seeded fault schedule -------
    from dhqr_tpu.serve.errors import DeadlineExceeded, Quarantined

    def _path_complete(fut, exc, recorder) -> "tuple[bool, list]":
        """THE tentpole acceptance predicate: a typed-error future's
        trace must reconstruct its complete path — admission, a
        dispatch attempt, and a typed resolution matching the error.
        The retry/isolate/bisect hop is additionally required for
        failures the scheduler escalates (DispatchFailed/CompileFailed/
        numeric); a DeadlineExceeded (budget ran out right after a
        failed dispatch) or a Quarantined (no headroom to absorb the
        cooldown) legitimately resolves typed straight from
        _handle_failure with no escalation hop — demanding one there
        would fail the benchmark on exactly-as-specified behavior."""
        tid = getattr(fut, "trace_id", None)
        if tid is None or getattr(exc, "trace_id", None) is None:
            return False, []
        spans = recorder.dump(tid)["spans"]
        names = [s["name"] for s in spans]
        resolve = [s for s in spans if s["name"] == "resolve"]
        needs_hop = not isinstance(exc, (DeadlineExceeded, Quarantined))
        ok = (bool(names) and names[0] == "submit"
              and names[-1] == "resolve" and "dispatch" in names
              and (not needs_hop
                   or any(h in names for h in
                          ("retry", "isolate", "bisect",
                           "numeric_isolate")))
              and resolve[-1]["outcome"] == type(exc).__name__)
        return ok, names

    offered_rps = rate_frac * statistics.median(disarmed)
    inter = np.random.default_rng(1).exponential(
        1.0 / offered_rps, size=n_requests)
    arrivals = np.cumsum(inter)
    dump_dir = tempfile.mkdtemp(prefix="dhqr_obs_flight_")

    _stage("chaos_traced")
    with _Watchdog("chaos_traced", 2400):
        fcfg = FaultConfig(
            sites=(("serve.dispatch", LIGHT_FAULTS["dispatch_p"], None),
                   ("serve.latency", LIGHT_FAULTS["latency_p"], None)),
            seed=7, latency_ms=LIGHT_FAULTS["latency_ms"])
        lat = LatencyHistogram()
        with obs.observed(ObsConfig(enabled=True, buffer_spans=65536,
                                    auto_dump=dump_dir)) as rec:
            sched = AsyncScheduler(
                serve_config=scfg,
                sched_config=SchedulerConfig(
                    slo_ms=SLO_MS, queue_depth=4096,
                    flush_interval_ms=FLUSH_INTERVAL_MS),
                cache=acache)
            harness = faults.install(fcfg)
            try:
                t_start = time.perf_counter()
                futs, rejected = [], 0
                for i in range(n_requests):
                    delay = t_start + arrivals[i] - time.perf_counter()
                    if delay > 0:
                        time.sleep(delay)
                    t_submit = time.perf_counter()
                    try:
                        fut = sched.submit("lstsq", As[i], bs[i],
                                           deadline=SLO_MS / 1e3,
                                           tenant=f"t{picks[i]}")
                    except ServeError:
                        rejected += 1
                        continue
                    fut.add_done_callback(
                        lambda f, t=t_submit:
                        lat.record(time.perf_counter() - t))
                    futs.append(fut)
                from concurrent.futures import wait as _wait
                _wait(futs, timeout=600)
                assert all(f.done() for f in futs), "futures hung"
                # The artifact's authoritative registry block: taken
                # HERE, while the scheduler instance, the armed fault
                # harness and the trace recorder are all still live —
                # after uninstall/GC their serve.sched.*/faults.*/obs.*
                # names would drop out of the snapshot (weak sources,
                # disarmed providers) and decision rule 5 would have
                # nothing to key on.
                registry_snap = obs.registry().snapshot()
            finally:
                faults.uninstall()
            sched_stats = sched.stats()
            sched.shutdown()

            # Every typed-error future's trace must reconstruct its
            # complete path (typed failures here depend on the seeded
            # schedule outrunning the retry budget; the typed_path
            # segment below guarantees deterministic evidence).
            typed, complete, incomplete = 0, 0, []
            for f in futs:
                exc = f.exception()
                if exc is None:
                    continue
                assert isinstance(exc, ServeError), exc
                typed += 1
                ok, names = _path_complete(f, exc, rec)
                if ok:
                    complete += 1
                else:
                    incomplete.append({"trace_id": getattr(f, "trace_id",
                                                           None),
                                       "path": names,
                                       "error": type(exc).__name__})
            recorder_stats = rec.stats()
        dump_file = os.path.join(dump_dir, f"flight_{os.getpid()}.jsonl")
        dumped = sum(1 for _ in open(dump_file)) \
            if os.path.exists(dump_file) else 0
    emit({"metric": "serving_obs", "phase": "chaos_traced",
          "requests": n_requests, "rejected": rejected,
          "accepted": len(futs),
          "offered_rps": round(offered_rps, 1),
          "typed_failures": typed,
          "typed_traces_complete": complete,
          "typed_traces_incomplete": incomplete[:5],
          "auto_dumped_records": dumped,
          "client_latency": lat.snapshot(),
          "recorder": recorder_stats,
          "injected": harness.stats(),
          "scheduler": {k: sched_stats[k] for k in (
              "completed", "failed", "retries", "bisections", "poisoned",
              "flush_failures", "deadline_misses", "dispatches")},
          "registry": registry_snap})

    # ---- deterministic typed-path segment ------------------------------
    _stage("typed_path")
    with _Watchdog("typed_path", 1200):
        with obs.observed(ObsConfig(enabled=True, buffer_spans=4096,
                                    auto_dump=dump_dir)) as rec2:
            psched = AsyncScheduler(
                serve_config=scfg, cache=acache, start=False,
                sched_config=SchedulerConfig(slo_ms=30e3,
                                             flush_interval_ms=5.0,
                                             max_retries=1,
                                             retry_base_ms=5.0))
            with faults.injected(FaultConfig(
                    sites=(("serve.dispatch", 1.0, None),), seed=3)):
                pfuts = [psched.submit("lstsq", As[i], bs[i], deadline=10.0)
                         for i in range(4)]
                t0 = time.perf_counter()
                while not all(f.done() for f in pfuts):
                    psched.poll()
                    if time.perf_counter() - t0 > 90:
                        raise RuntimeError(
                            f"typed_path hung: {psched.stats()}")
                    time.sleep(0.002)
            psched.shutdown()
            typed2, complete2, paths2 = 0, 0, []
            for f in pfuts:
                exc = f.exception()
                assert isinstance(exc, ServeError), exc
                typed2 += 1
                ok, names = _path_complete(f, exc, rec2)
                complete2 += int(ok)
                paths2.append(names)
        dumped = sum(1 for _ in open(dump_file)) \
            if os.path.exists(dump_file) else 0
    emit({"metric": "serving_obs", "phase": "typed_path",
          "typed_failures": typed2, "typed_traces_complete": complete2,
          "example_path": paths2[0] if paths2 else [],
          "auto_dumped_records_total": dumped})

    # ---- verdict -------------------------------------------------------
    typed_total = typed + typed2
    complete_total = complete + complete2
    ok = (overhead_ratio >= 0.95 and armed_recompiles == 0
          and typed_total > 0 and complete_total == typed_total
          and typed2 == 4 == complete2
          and dumped >= typed_total
          and all(f.done() for f in futs))
    emit({"metric": "serving_obs_verdict",
          "armed_over_disarmed": round(overhead_ratio, 4),
          "armed_within_5pct": overhead_ratio >= 0.95,
          "zero_recompiles_armed": armed_recompiles == 0,
          "typed_failures": typed_total,
          "every_typed_trace_complete": complete_total == typed_total,
          "deterministic_typed_paths": typed2 == 4 == complete2,
          "auto_dump_covers_typed": dumped >= typed_total,
          "ok": bool(ok)})
    _stage("done")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 384,
         float(sys.argv[2]) if len(sys.argv) > 2 else 0.90)
