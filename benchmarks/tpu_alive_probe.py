"""Minimal relay-health probe: is the axon TPU tunnel answering?

Two stages, each with its own watchdog and a ``::stage`` marker:

1. ``backend_init`` — ``jax.devices()`` + client bring-up only. This is
   where a wedged relay hangs (BENCH_r02/r03 both died here), and it
   involves NO remote compile, so a watchdog hard-exit here cannot
   re-wedge the relay (the 5-hour wedge of round 3 was caused by a hard
   exit DURING a remote compile — see the session notes / memory).

CAVEAT (measured round 5): when the main thread blocks inside the PJRT
C++ init *without releasing the GIL*, the watchdog thread stalls on its
own ``print`` and never reaches ``os._exit`` — the probe then hangs
past every internal deadline. Callers MUST wrap the probe in an outer
kernel-level kill (``timeout -k 30 900 python benchmarks/tpu_alive_probe.py``);
``tpu_watch_and_run.sh`` does.
2. ``tiny_matmul`` — one 128x128 f32 matmul, 600 s watchdog (long enough
   that the hard exit only fires on a true hang, never a slow compile).

Prints one JSON line: {"alive": bool, "stage": ..., "seconds": ...}.
Exit code 0 = alive, 2 = not alive (watchdog or error).

Usage: python benchmarks/tpu_alive_probe.py
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


class _Watchdog:
    def __init__(self, stage: str, seconds: float):
        self._stage, self._seconds = stage, seconds
        self._done = threading.Event()
        self._t = threading.Thread(target=self._fire, daemon=True)

    def _fire(self):
        if not self._done.wait(self._seconds):
            print(json.dumps({"alive": False, "stage": self._stage,
                              "why": f"watchdog {self._seconds}s"}),
                  flush=True)
            os._exit(2)

    def __enter__(self):
        self._t.start()
        return self

    def __exit__(self, *exc):
        self._done.set()


def main() -> int:
    # NO custom SIGTERM handler: a Python-level handler only runs between
    # bytecodes, so a probe blocked inside the PJRT C++ init (the round-4/5
    # wedge signature) would shrug off SIGTERM entirely — round 5 measured
    # exactly that (handler installed -> `timeout` couldn't kill it; default
    # disposition -> rc=143 immediately). The kernel-level default is the
    # only exit path that always works, and the probe has no cleanup needs.
    t_start = time.time()
    _stage("import_jax")
    import jax
    import jax.numpy as jnp

    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

    _stage("backend_init")
    with _Watchdog("backend_init", 240):
        devs = jax.devices()
        platform = devs[0].platform
        kind = devs[0].device_kind
    _stage("tiny_matmul")
    with _Watchdog("tiny_matmul", 600):
        x = jnp.ones((128, 128), dtype=jnp.float32)
        y = (x @ x)[0, 0]
        float(y)  # scalar readback = completion fence under the tunnel
    dt = time.time() - t_start
    print(json.dumps({"alive": True, "platform": platform,
                      "device_kind": kind, "seconds": round(dt, 1)}),
          flush=True)
    return 0 if platform == "tpu" else 2


if __name__ == "__main__":
    sys.exit(main())
