"""Serving-tier throughput ladder: mixed-shape stream, batched vs singles.

The round-8 tentpole's decision artifact: a Zipf-ish stream of
heterogeneous least-squares requests (small shapes dominate, as in a
serving mix) is fed through

* ``dhqr_tpu.serve.batched_lstsq`` in arrival micro-batches (the
  serving path: bucket -> stack -> one vmapped AOT-cached dispatch per
  bucket group), and
* a loop of single ``dhqr_tpu.lstsq`` dispatches (the pre-serve
  baseline), warm (its per-shape jit compiles already paid).

Reported per phase: requests/s, recompile count (serve cache counters),
p50/p99 dispatch latency, and — on the first warm pass — EVERY request's
normal-equations residual against the reference's 8x LAPACK criterion
(runtests.jl:62), so the throughput claim is never bought with accuracy.

Acceptance (ISSUE 3): on the second pass of the repeated stream the
serve cache must show ZERO recompiles, and batched requests/s must be
>= 3x the singles loop at n <= 256, micro-batch >= 32, all residuals
within the 8x criterion.

Usage:  python benchmarks/serving_throughput.py [n_requests]
Writes: benchmarks/results/serving_throughput_<platform>.jsonl (append).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# The request-shape ladder (rank-weighted: weight ~ 1/(rank+1)^1.1, the
# Zipf-ish mix of a many-small-tenants serving tier). All n <= 256. Half
# the entries sit exactly on the half-octave bucket lattice (the common
# MXU-friendly sizes a tuned tenant sends), half are awkward and pay the
# tier's real padding cost — the measured requests/s includes both.
SHAPE_LADDER = [
    (64, 16), (100, 36), (128, 48), (192, 64),
    (250, 100), (384, 128), (500, 180), (640, 256),
]
MICRO_BATCH = 32
WARM_PASSES = 5


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def _pctl(xs, q):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def main(n_requests: int = 256) -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    from bench import SCHEMA_VERSION, ROUND, _Watchdog

    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np

    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(_REPO, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

    import dhqr_tpu
    from dhqr_tpu.serve import batched_lstsq
    from dhqr_tpu.serve.cache import ExecutableCache
    from dhqr_tpu.utils.profiling import sync
    from dhqr_tpu.utils.testing import (TOLERANCE_FACTOR,
                                        normal_equations_residual,
                                        oracle_residual)

    _stage("backend_init")
    with _Watchdog("backend_init", 240):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    out_path = os.path.join(_REPO, "benchmarks", "results",
                            f"serving_throughput_{platform}.jsonl")

    def emit(rec):
        rec.update(platform=platform, device_kind=kind, round=ROUND,
                   schema_version=SCHEMA_VERSION)
        line = json.dumps(rec)
        print(line, flush=True)
        with open(out_path, "a") as f:
            f.write(line + "\n")

    # ---- the request stream (fixed seed: artifact is reproducible) ----
    rng = np.random.default_rng(0)
    ranks = np.arange(len(SHAPE_LADDER))
    weights = 1.0 / (ranks + 1.0) ** 1.1
    weights /= weights.sum()
    picks = rng.choice(len(SHAPE_LADDER), size=n_requests, p=weights)
    shapes = [SHAPE_LADDER[i] for i in picks]
    As = [jnp.asarray(rng.random(s), jnp.float32) for s in shapes]
    bs = [jnp.asarray(rng.random(s[0]), jnp.float32) for s in shapes]
    sync(As[-1])
    micro = [list(range(lo, min(lo + MICRO_BATCH, n_requests)))
             for lo in range(0, n_requests, MICRO_BATCH)]

    cache = ExecutableCache(max_size=64)

    def serve_pass():
        """One full pass in arrival micro-batches; returns (per-dispatch
        seconds, results in input order)."""
        lat, out = [], [None] * n_requests
        for group in micro:
            t0 = time.perf_counter()
            xs = batched_lstsq([As[i] for i in group],
                               [bs[i] for i in group], cache=cache)
            sync(xs)
            lat.append(time.perf_counter() - t0)
            for i, x in zip(group, xs):
                out[i] = x
        return lat, out

    # ---- cold pass: compiles happen here, counted -----------------------
    _stage("serve_cold")
    with _Watchdog("serve_cold", 1200):
        t0 = time.perf_counter()
        _, xs_cold = serve_pass()
        cold_s = time.perf_counter() - t0
    s_cold = cache.stats()
    emit({"metric": "serving_throughput", "phase": "cold",
          "requests": n_requests, "micro_batch": MICRO_BATCH,
          "distinct_shapes": len(SHAPE_LADDER),
          "recompiles": s_cold["misses"], "seconds": round(cold_s, 3),
          "cache": s_cold})

    # ---- residuals: every request against the 8x LAPACK criterion ------
    _stage("residuals")
    worst = 0.0
    all_ok = True
    for A, b, x in zip(As, bs, xs_cold):
        res = normal_equations_residual(A, np.asarray(x), b)
        ref = oracle_residual(np.asarray(A), np.asarray(b))
        ratio = res / (TOLERANCE_FACTOR * ref)
        worst = max(worst, ratio)
        all_ok = all_ok and ratio < 1.0
    emit({"metric": "serving_residuals", "requests": n_requests,
          "criterion": "8x_lapack_normal_equations",
          "all_within": all_ok, "worst_fraction_of_bar": round(worst, 4)})

    # ---- warm repeat passes: the zero-recompile contract ---------------
    _stage("serve_warm")
    with _Watchdog("serve_warm", 1200):
        misses_before = cache.stats()["misses"]
        lat_all = []
        t0 = time.perf_counter()
        for _ in range(WARM_PASSES):
            lat, _ = serve_pass()
            lat_all.extend(lat)
        warm_s = time.perf_counter() - t0
    recompiles_warm = cache.stats()["misses"] - misses_before
    serve_rps = n_requests * WARM_PASSES / warm_s
    emit({"metric": "serving_throughput", "phase": "warm_repeat",
          "passes": WARM_PASSES, "requests": n_requests,
          "micro_batch": MICRO_BATCH, "recompiles": recompiles_warm,
          "requests_per_s": round(serve_rps, 1),
          "dispatch_p50_ms": round(_pctl(lat_all, 0.50) * 1e3, 2),
          "dispatch_p99_ms": round(_pctl(lat_all, 0.99) * 1e3, 2),
          "cache": cache.stats()})

    # ---- singles baseline: loop of one-shot lstsq dispatches -----------
    _stage("singles_warmup")
    with _Watchdog("singles_warmup", 1200):
        for m, n in SHAPE_LADDER:  # pay each shape's jit compile up front
            x = dhqr_tpu.lstsq(jnp.zeros((m, n), jnp.float32) +
                               jnp.eye(m, n, dtype=jnp.float32),
                               jnp.ones((m,), jnp.float32))
            sync(x)
    _stage("singles")
    with _Watchdog("singles", 1200):
        lat_s = []
        t0 = time.perf_counter()
        for _ in range(WARM_PASSES):
            for A, b in zip(As, bs):
                t1 = time.perf_counter()
                x = dhqr_tpu.lstsq(A, b)
                sync(x)
                lat_s.append(time.perf_counter() - t1)
        singles_s = time.perf_counter() - t0
    singles_rps = n_requests * WARM_PASSES / singles_s
    emit({"metric": "serving_throughput", "phase": "singles",
          "passes": WARM_PASSES, "requests": n_requests,
          "warm_compiles": len(SHAPE_LADDER),
          "requests_per_s": round(singles_rps, 1),
          "dispatch_p50_ms": round(_pctl(lat_s, 0.50) * 1e3, 2),
          "dispatch_p99_ms": round(_pctl(lat_s, 0.99) * 1e3, 2)})

    # ---- verdict -------------------------------------------------------
    speedup = serve_rps / singles_rps
    emit({"metric": "serving_verdict",
          "speedup_vs_singles": round(speedup, 2),
          "speedup_target": 3.0,
          "zero_recompiles_on_repeat": recompiles_warm == 0,
          "all_residuals_within_8x": all_ok,
          "max_n": max(n for _, n in SHAPE_LADDER),
          "micro_batch": MICRO_BATCH,
          "ok": bool(speedup >= 3.0 and recompiles_warm == 0 and all_ok)})
    _stage("done")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 256)
