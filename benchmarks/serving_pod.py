"""dhqr-pod acceptance: hierarchical collectives on two-tier pod meshes.

The round-20 decision artifact (benchmarks/README "Round-20 decision
rules"): every sharded engine family x simulated CPU topology in
{1x8, 2x4, 4x2} x schedule in {flat, hierarchical} x comms rung in
{f32, dcn:bf16},

1. **traced cross-DCN volume** — the dhqr-audit jaxpr census split by
   axis name (``analysis.comms_pass.CommsStats.dcn_volume_bytes``): a
   flat schedule names the "dcn" axis in every joint collective, so its
   whole payload crosses the slow tier; the hierarchical schedule must
   shrink the crossing bytes by >= ici_size (the reduce-inside-ICI
   chunking — e.g. >= 4x at 2x4), the same split DHQR302's per-tier
   budget column enforces statically in ``tools/lint.sh``;
2. **accuracy** — a real solve per cell, normal-equations residual
   within the reference 8x-LAPACK criterion at BOTH rungs: dcn:bf16
   compresses only the isolated DCN crossing (f32 inside the ICI
   domain), and the column engines route compressed cells through the
   model tier whose CSNE floor is part of the rung's contract;
3. **zero warm recompiles** — each (topology, schedule, rung) cell
   compiles once; warm repeats count zero ``backend_compile`` events
   (``jax.monitoring``), so topology is a cache key, not a rebuild.

Ends with a ``serving_pod_verdict`` row the regress gate's ``pod-*``
rules enforce from then on.

Usage:  python benchmarks/serving_pod.py
Writes: benchmarks/results/serving_pod_<platform>.jsonl (append)
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

#: Simulated two-tier factorizations of P = 8 (DHQR_TOPO grammar,
#: parallel/topology.parse_topo): dcn_size x ici_size. 1x8 is the
#: degenerate single-tier pod — its hierarchical schedule must cross
#: the DCN axis zero times.
TOPOLOGIES = ("1x8", "2x4", "4x2")
MODES = (None, "dcn:bf16")
#: Engine families traced for the cross-DCN ratio; every family must
#: meet the bar at every dcn_size > 1 topology.
FAMILIES = ("unblocked_qr", "blocked_qr", "sharded_solve",
            "tsqr_lstsq", "cholqr_lstsq")


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main() -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    rnd = int(os.environ.get("DHQR_ROUND", "20"))
    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import monitoring

    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(_REPO, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

    from bench import SCHEMA_VERSION, _Watchdog

    compiles = {"n": 0}
    monitoring.register_event_duration_secs_listener(
        lambda name, *a, **k: compiles.__setitem__("n", compiles["n"] + 1)
        if name == "/jax/core/compile/backend_compile_duration" else None)

    from dhqr_tpu.analysis.comms_pass import collect_comms
    from dhqr_tpu.models.qr_model import lstsq as model_lstsq
    from dhqr_tpu.parallel.mesh import pod_mesh
    from dhqr_tpu.parallel.sharded_cholqr import sharded_cholqr_lstsq
    from dhqr_tpu.parallel.sharded_qr import (
        sharded_blocked_qr,
        sharded_householder_qr,
    )
    from dhqr_tpu.parallel.sharded_solve import sharded_lstsq, sharded_solve
    from dhqr_tpu.parallel.sharded_tsqr import sharded_tsqr_lstsq
    from dhqr_tpu.utils.profiling import sync
    from dhqr_tpu.utils.testing import (
        TOLERANCE_FACTOR,
        normal_equations_residual,
        oracle_residual,
    )

    _stage("backend_init")
    with _Watchdog("backend_init", 240):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    out_path = os.path.join(_REPO, "benchmarks", "results",
                            f"serving_pod_{platform}.jsonl")
    navail = len(jax.devices())
    if navail < 8:
        # The dryrun-pod-stage convention: without 8 devices none of the
        # simulated factorizations exist — say so loudly instead of
        # crashing on pod_mesh (XLA_FLAGS is read once at init, so a
        # pre-set flag string without the device-count flag lands here).
        print("serving_pod: SKIPPED (needs 8 devices; set XLA_FLAGS="
              "--xla_force_host_platform_device_count=8 before the first "
              "backend touch)", file=sys.stderr, flush=True)
        return

    def emit(rec):
        rec.update(platform=platform, device_kind=kind, round=rnd,
                   schema_version=SCHEMA_VERSION)
        line = json.dumps(rec)
        print(line, flush=True)
        with open(out_path, "a") as f:
            f.write(line + "\n")

    rng = np.random.default_rng(0)
    P = 8
    n, nb = 8 * P, 4
    m = 2 * n
    mt, nt = 64 * P, 32  # tall-skinny row-engine shapes (serving_wire note)
    A = jnp.asarray(rng.random((m, n)), jnp.float32)
    b = jnp.asarray(rng.random(m), jnp.float32)
    At = jnp.asarray(rng.random((mt, nt)), jnp.float32)
    bt = jnp.asarray(rng.random(mt), jnp.float32)

    def cells():
        """(topo label, mesh, hierarchical TierAxes, flat TierAxes,
        ici_size) per simulated factorization."""
        for topo in TOPOLOGIES:
            pmesh, taxes = pod_mesh(P, topo=topo)
            flat = dataclasses.replace(taxes, hierarchical=False)
            yield topo, pmesh, taxes, flat, taxes.ici_size

    def tracers(pmesh, axis):
        """(family, comms -> closed-jaxpr thunk) per engine family on
        one (mesh, schedule) cell. H/alpha for the solve tracer come
        from a plain factor on the same cell so shapes line up."""
        H, alpha = jax.eval_shape(
            lambda A: sharded_blocked_qr(A, pmesh, block_size=nb,
                                         axis_name=axis), A)
        Hc = jnp.zeros(H.shape, H.dtype)
        ac = jnp.zeros(alpha.shape, alpha.dtype)
        yield ("unblocked_qr", lambda c: jax.make_jaxpr(
            lambda A: sharded_householder_qr(A, pmesh, axis_name=axis,
                                             comms=c))(A))
        yield ("blocked_qr", lambda c: jax.make_jaxpr(
            lambda A: sharded_blocked_qr(A, pmesh, block_size=nb,
                                         axis_name=axis, comms=c))(A))
        yield ("sharded_solve", lambda c: jax.make_jaxpr(
            lambda H, a, b: sharded_solve(H, a, b, pmesh, block_size=nb,
                                          axis_name=axis, comms=c)
        )(Hc, ac, b))
        yield ("tsqr_lstsq", lambda c: jax.make_jaxpr(
            lambda A, b: sharded_tsqr_lstsq(A, b, pmesh, block_size=8,
                                            axis_name=axis, comms=c)
        )(At, bt))
        yield ("cholqr_lstsq", lambda c: jax.make_jaxpr(
            lambda A, b: sharded_cholqr_lstsq(A, b, pmesh, axis_name=axis,
                                              comms=c))(At, bt))

    def runners(pmesh, axis):
        """(family, comms -> x, residual problem) per family. The
        column families route COMPRESSED cells through the model tier,
        whose CSNE refinement floor is part of the dcn:* rung contract
        (models/qr_model.lstsq); f32 cells run the engines directly."""
        yield ("blocked_qr", lambda c: model_lstsq(
            A, b, mesh=pmesh, block_size=nb, comms=c, mesh_axis=axis)
            if c else sharded_lstsq(A, b, pmesh, block_size=nb,
                                    axis_name=axis), (A, b))
        yield ("sharded_solve", lambda c: model_lstsq(
            A, b, mesh=pmesh, block_size=nb, blocked=False, comms=c,
            mesh_axis=axis)
            if c else sharded_lstsq(A, b, pmesh, block_size=nb,
                                    axis_name=axis), (A, b))
        yield ("tsqr_lstsq", lambda c: sharded_tsqr_lstsq(
            At, bt, pmesh, block_size=8, axis_name=axis, comms=c), (At, bt))
        yield ("cholqr_lstsq", lambda c: sharded_cholqr_lstsq(
            At, bt, pmesh, axis_name=axis, comms=c), (At, bt))

    # ---- phase 1: traced cross-DCN volume, hierarchical vs flat ---------
    _stage("traced_dcn_volume")
    ratio_ok = True
    with _Watchdog("traced_dcn_volume", 1800):
        for topo, pmesh, taxes, flat, ici in cells():
            hier_tracers = dict((f, t) for f, t in tracers(pmesh, taxes))
            flat_tracers = dict((f, t) for f, t in tracers(pmesh, flat))
            for family in FAMILIES:
                for comms in MODES:
                    dcn_hier = collect_comms(
                        hier_tracers[family](comms)).dcn_volume_bytes()
                    dcn_flat = collect_comms(
                        flat_tracers[family](comms)).dcn_volume_bytes()
                    ratio = dcn_flat / max(dcn_hier, 1)
                    # bar: the chunked DCN exchange is exactly 1/ici of
                    # the flat payload (cost_model.tiered_budget_bytes is
                    # byte-exact), so >= ici with only float headroom.
                    ok = ratio >= ici * (1 - 1e-9)
                    ratio_ok = ratio_ok and ok
                    emit({
                        "metric": "serving_pod_dcn_volume",
                        "engine": family, "topology": topo,
                        "comms": comms or "f32",
                        "value": round(ratio, 4),
                        "unit": "flat cross-DCN bytes / hierarchical "
                                "cross-DCN bytes",
                        "dcn_bytes_flat": dcn_flat,
                        "dcn_bytes_hierarchical": dcn_hier,
                        "ratio_bar": ici,
                        "meets_bar": bool(ok),
                    })

    # ---- phase 2: accuracy across the matrix ----------------------------
    _stage("residuals")
    worst = 0.0
    cells_n = gated = 0
    with _Watchdog("residuals", 3600):
        for topo, pmesh, taxes, flat, _ici in cells():
            for sched, axis in (("hierarchical", taxes), ("flat", flat)):
                for family, run, (Aref, bref) in runners(pmesh, axis):
                    ref = oracle_residual(np.asarray(Aref), np.asarray(bref))
                    for comms in MODES:
                        x = run(comms)
                        res = normal_equations_residual(
                            Aref, np.asarray(x), bref)
                        ratio = res / ref if ref > 0 else float(res > 0)
                        cells_n += 1
                        gated += ratio < TOLERANCE_FACTOR
                        worst = max(worst, ratio)
                        emit({
                            "metric": "serving_pod_residual",
                            "engine": family, "topology": topo,
                            "schedule": sched, "comms": comms or "f32",
                            "value": round(ratio, 4),
                            "unit": "normal-equations residual / LAPACK "
                                    "oracle",
                            "residual_criterion": TOLERANCE_FACTOR,
                            "within_8x": bool(ratio < TOLERANCE_FACTOR),
                        })

    # ---- phase 3: zero warm recompiles per cell -------------------------
    _stage("warm_recompiles")
    warm_recompiles = 0
    with _Watchdog("warm_recompiles", 1800):
        for topo, pmesh, taxes, flat, _ici in cells():
            for sched, axis in (("hierarchical", taxes), ("flat", flat)):
                for comms in MODES:
                    # cold pass compiles; the counter window opens after.
                    sync(sharded_blocked_qr(A, pmesh, block_size=nb,
                                            axis_name=axis, comms=comms))
                    sync(sharded_tsqr_lstsq(At, bt, pmesh, block_size=8,
                                            axis_name=axis, comms=comms))
                    before = compiles["n"]
                    sync(sharded_blocked_qr(A, pmesh, block_size=nb,
                                            axis_name=axis, comms=comms))
                    sync(sharded_tsqr_lstsq(At, bt, pmesh, block_size=8,
                                            axis_name=axis, comms=comms))
                    delta = compiles["n"] - before
                    warm_recompiles += delta
                    emit({"metric": "serving_pod_recompiles",
                          "topology": topo, "schedule": sched,
                          "comms": comms or "f32",
                          "warm_recompiles": delta})

    # ---- verdict --------------------------------------------------------
    ok = ratio_ok and gated == cells_n and warm_recompiles == 0
    emit({
        "metric": "serving_pod_verdict",
        "kind": "verdict",
        "value": round(worst, 4),
        "unit": "worst normal-equations residual ratio over the matrix",
        "dcn_ratio_meets_bar": bool(ratio_ok),
        "residual_cells": cells_n,
        "residual_cells_within_8x": gated,
        "worst_residual_ratio": round(worst, 4),
        "warm_recompiles": warm_recompiles,
        "topologies": list(TOPOLOGIES),
        "ok": bool(ok),
    })
    _stage("done")


if __name__ == "__main__":
    main()
