"""Round-3 TPU probe: large-size retest with a healthy compile helper.

tpu_r3_disambig.jsonl proved the earlier 18432-24576 "failures" were
collateral from a crashed compile helper (a failed c64 compile poisons the
process), and 18432^2 actually works. This probe, run FIRST in a fresh
process with no complex stages at all, measures the real size ceiling:
24576^2 and 28672^2, nb=512 all-Pallas (the v5e gate admits 50 / 58.7 MB
panels). 32768^2 stays excluded — its buffer is exactly 2^32 bytes, a
genuine per-buffer addressing limit.

Single-dispatch timing: device time (>= 0.6 s) dwarfs the 60-90 ms RTT.

Run ONE instance at a time (the axon relay allows a single TPU process).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main() -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    from bench import _Watchdog

    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from dhqr_tpu.ops.blocked import _blocked_qr_impl, _blocked_qr_impl_donate
    from dhqr_tpu.utils.profiling import sync

    _stage("backend_init")
    with _Watchdog("backend_init", 150):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    rng = np.random.default_rng(0)

    def emit(rec):
        rec["platform"] = platform
        rec["device_kind"] = kind
        print(json.dumps(rec), flush=True)

    def qr_stage(n, nb, watchdog, repeats=2, donate=False):
        """One capacity/timing stage. ``donate=True`` runs the DONATING
        engine: XLA may alias the input buffer into the output, saving
        one full matrix of HBM — the lever that decides whether 28672^2
        (OOM on the non-donating jit, round 3) fits the chip. For that
        path A is generated ON DEVICE per dispatch (donation invalidates
        it, and re-uploading 3.3 GB through the tunnel would dwarf the
        measurement), and the previous dispatch's outputs are dropped
        BEFORE the next A exists — holding them across the call would
        restore the 2-matrix peak donation is meant to avoid."""
        impl = _blocked_qr_impl_donate if donate else _blocked_qr_impl
        name = f"qr_f32_{n}_nb{nb}" + ("_donate" if donate else "")
        _stage(name)
        try:
            with _Watchdog(name, watchdog):
                if donate:
                    make = jax.jit(
                        lambda k: jax.random.uniform(k, (n, n), jnp.float32))
                    A = make(jax.random.key(0))
                else:
                    A = jnp.asarray(rng.random((n, n)), jnp.float32)
                sync(A)
                t0 = time.perf_counter()
                comp = impl.lower(
                    A, nb, precision="highest", pallas=True,
                    norm="fast").compile()
                H, al = comp(A)
                sync(al)
                compile_s = time.perf_counter() - t0
                ts = []
                for i in range(repeats):
                    if donate:
                        H = al = A = None  # free before the next make()
                        A = make(jax.random.key(i + 1))
                        sync(A)
                    t0 = time.perf_counter()
                    H, al = comp(A)
                    sync(al)
                    ts.append(time.perf_counter() - t0)
                t1 = min(ts)
                rec = {"metric": f"qr_gflops_per_chip_f32_{n}x{n}",
                       "value": round((4.0 / 3.0) * n**3 / t1 / 1e9, 2),
                       "unit": "GFLOP/s", "block_size": nb,
                       "pallas_panels": True, "seconds": round(t1, 4),
                       "compile_seconds": round(compile_s, 2),
                       "note": ("donating engine; single-dispatch"
                                if donate else
                                "single-dispatch; device time >> RTT")}
                if donate:
                    rec["donate"] = True
                emit(rec)
        except Exception as ex:
            emit({"metric": name, "ok": False,
                  "error": f"{type(ex).__name__}: {ex}"[:300]})

    qr_stage(24576, 512, 560)
    # Donating control at a size that already fits: quantifies any cost
    # of the aliased program before the capacity attempt below.
    qr_stage(24576, 512, 560, donate=True)
    qr_stage(28672, 512, 560)
    # The capacity attempt: one matrix of HBM saved by donation is
    # exactly the margin 28672^2 missed in round 3.
    qr_stage(28672, 512, 560, donate=True)
    _stage("done")


if __name__ == "__main__":
    main()
