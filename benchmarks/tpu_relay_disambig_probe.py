"""Round-3 TPU probe: disambiguate real UNIMPLEMENTED ops from relay
compile-helper collateral damage.

tpu_r3_c64_diag.jsonl shows EVERY stage failing UNIMPLEMENTED — including
f32 shapes adjacent to ones that measured fine minutes earlier. The
suspicious timeline: each earlier probe's first c64 *Mosaic* compile
crashed the relay's compile helper (HTTP 500 "tpu_compile_helper
subprocess exit code 1"), after which every subsequent compile in the
session failed generically. So stage ORDER here is the experiment:

1. uncached f32 QR (768^2, nb=64 — never compiled before): compile-helper
   health check in a fresh process;
2. f32 QR 18432^2 nb=512: the "size limit" claim, re-tested while healthy;
3. c64 matmul 256^2 (pure XLA): is complex64 genuinely unimplemented?
4. uncached f32 again (640^2): did stage 3 poison the helper for
   non-complex work too?

Run ONE instance at a time (the axon relay allows a single TPU process).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main() -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    from bench import _Watchdog

    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from dhqr_tpu.ops.blocked import _blocked_qr_impl
    from dhqr_tpu.utils.profiling import sync

    _stage("backend_init")
    with _Watchdog("backend_init", 150):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    rng = np.random.default_rng(0)

    def emit(rec):
        rec["platform"] = platform
        rec["device_kind"] = kind
        print(json.dumps(rec), flush=True)

    def try_stage(name, fn, watchdog=240):
        _stage(name)
        try:
            with _Watchdog(name, watchdog):
                t0 = time.perf_counter()
                fn()
                emit({"metric": name, "ok": True,
                      "seconds_total": round(time.perf_counter() - t0, 2)})
                return True
        except Exception as ex:
            emit({"metric": name, "ok": False,
                  "error": f"{type(ex).__name__}: {ex}"[:300]})
            return False

    def f32_qr(n, nb):
        def run():
            A = jnp.asarray(rng.random((n, n)), jnp.float32)
            sync(A)
            H, al = _blocked_qr_impl(A, nb, precision="highest", pallas=True,
                                     norm="fast")
            sync(al)
        return run

    def c64_matmul():
        C = jnp.asarray(rng.random((256, 256)) + 1j * rng.random((256, 256)),
                        jnp.complex64)
        r = jnp.matmul(C, C, precision="highest")
        sync(jnp.abs(r[0, 0]))

    try_stage("f32_qr_768_nb64_fresh", f32_qr(768, 64))
    try_stage("f32_qr_18432_nb512", f32_qr(18432, 512), watchdog=560)
    try_stage("c64_matmul_256", c64_matmul)
    try_stage("f32_qr_640_nb64_after_c64", f32_qr(640, 64))
    _stage("done")


if __name__ == "__main__":
    main()
