"""Device-scaling curve for the sharded engines (VERDICT r1 item 4).

Runs the distributed compact-WY QR (and optionally the full least-squares
pipeline) at a fixed problem size over meshes of 1, 2, 4, ... devices and
prints one JSON line per point plus a summary speedup table. On a machine
without a multi-chip TPU this exercises the virtual CPU mesh
(``--xla_force_host_platform_device_count``), where XLA executes the SPMD
partitions on host threads — real parallel execution and real collective
costs (through shared memory), the same proof-shape as the reference's
``addprocs(np)`` local cluster benchmarks (reference test/runtests.jl:84-89).

Interpreting the curve: per panel, every device factors an (m-k) x nb panel
redundantly (wall-clock-free in SPMD — all devices would otherwise idle
waiting on the owner) and one psum moves the panel, which every device needs
for its trailing update anyway. The scalable term is the trailing update,
whose per-device width shrinks as nloc = n/P. Efficiency is therefore
bounded by (trailing flops)/(total flops) — Amdahl on the panel tier.

Usage:
    python benchmarks/scaling.py [--n 1024] [--m 1024] [--nb 64]
                                 [--devices 1,2,4,8] [--repeats 3]
                                 [--layout cyclic] [--lstsq]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def main(argv=None) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--n", type=int, default=1024)
    parser.add_argument("--m", type=int, default=None, help="rows (default n)")
    parser.add_argument("--nb", type=int, default=64)
    parser.add_argument("--devices", default="1,2,4,8")
    parser.add_argument("--repeats", type=int, default=3)
    parser.add_argument("--layout", default="cyclic", choices=["block", "cyclic"])
    parser.add_argument("--lstsq", action="store_true",
                        help="time factor+solve instead of factor only")
    parser.add_argument("--panel-impl", default="loop",
                        choices=["loop", "recursive"],
                        help="panel-interior engine (the replicated panel is "
                        "the curve's Amdahl term — see module docstring)")
    args = parser.parse_args(argv)

    # Hardware needs explicit opt-in (DHQR_BENCH_TPU=1 or JAX_PLATFORMS
    # naming tpu): ambient axon + a wedged relay would hang the first
    # backend touch (round-4 hardening; shared recipe in _axon_env).
    # Parse --devices ONCE here; the sweep below reuses this list.
    counts = [int(tok) for tok in args.devices.split(",")]
    from _axon_env import default_to_virtual_cpu

    default_to_virtual_cpu(max(counts))

    import jax

    from dhqr_tpu.utils.platform import (
        cpu_requested,
        enable_compile_cache,
        force_cpu_platform,
    )

    if cpu_requested():
        force_cpu_platform()
    enable_compile_cache()
    import jax.numpy as jnp
    import numpy as np

    from dhqr_tpu.ops.blocked import _blocked_qr_impl
    from dhqr_tpu.parallel.mesh import column_mesh
    from dhqr_tpu.parallel.sharded_qr import sharded_blocked_qr
    from dhqr_tpu.parallel.sharded_solve import sharded_lstsq
    from dhqr_tpu.utils.profiling import sync

    m = args.m or args.n
    n, nb = args.n, args.nb
    ndev = len(jax.devices())
    rng = np.random.default_rng(0)
    A = jnp.asarray(rng.random((m, n)), dtype=jnp.float32)
    b = jnp.asarray(rng.random(m), dtype=jnp.float32)
    flops = 2.0 * m * n * n - (2.0 / 3.0) * n**3

    def bench(fn):
        out = fn()
        sync(out)
        times = []
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            out = fn()
            sync(out)
            times.append(time.perf_counter() - t0)
        return min(times)

    results = {}
    for P in counts:
        if P > ndev:
            print(json.dumps({"devices": P, "skipped": f"only {ndev} visible"}))
            continue
        if P == 1:
            fn = lambda: _blocked_qr_impl(A, nb, panel_impl=args.panel_impl)
            if args.lstsq:
                import dhqr_tpu
                fn = lambda: dhqr_tpu.lstsq(A, b, block_size=nb,
                                            panel_impl=args.panel_impl)
        else:
            mesh = column_mesh(P)
            if n % P or (n // P) % nb:
                print(json.dumps(
                    {"devices": P, "skipped": f"n={n} not divisible by P*nb"}))
                continue
            if args.lstsq:
                fn = lambda: sharded_lstsq(A, b, mesh, block_size=nb,
                                           layout=args.layout,
                                           panel_impl=args.panel_impl)
            else:
                fn = lambda: sharded_blocked_qr(A, mesh, block_size=nb,
                                                layout=args.layout,
                                                panel_impl=args.panel_impl)
        t = bench(fn)
        results[P] = t
        print(json.dumps({
            "metric": "sharded_lstsq" if args.lstsq else "sharded_blocked_qr",
            "devices": P, "layout": args.layout if P > 1 else "single",
            "shape": f"{m}x{n}", "block_size": nb,
            "panel_impl": args.panel_impl,
            "seconds": round(t, 4),
            "gflops": round(flops / t / 1e9, 2),
            "speedup_vs_1": round(results.get(1, t) / t, 3) if 1 in results else None,
            "platform": jax.default_backend(),
        }))


if __name__ == "__main__":
    main()
