"""dhqr-fleet acceptance: disk executable store + replica router.

The round-22 decision artifact (benchmarks/README "Round-22 decision
rules"):

1. **cold-start ladder** — three CHILD interpreters serve the same
   request mix: no store, store-cold (pays the compiles, publishes the
   blobs), store-warm (a new replica on the populated store). The warm
   child must report ZERO compiles — every executable arrives by
   deserialization (``fleet.store`` disk_hits) — and its cold-start
   wall (first-request latency, compile included for the others) must
   beat the compiling children;
2. **router capacity** — an open-loop request burst through a
   ``Router`` over K=3 in-process replicas vs the single-scheduler
   baseline, same shared cache (the router composes throughput, it
   must not tax it);
3. **replica-kill ladder** — K=3 replicas under a live stream, killed
   one by one: every accepted future resolves (result or typed
   ``ServeError``), survivors serve new work after each kill;
4. **store overhead** — a warm serving loop with the store attached
   holds >= 0.95x the store-less loop with zero recompiles (warm
   dispatch never touches the disk tier).

Ends with a ``serving_fleet_verdict`` row the regress gate's
``fleet-*`` rules enforce from then on.

Usage:  python benchmarks/serving_fleet.py
Writes: benchmarks/results/serving_fleet_<platform>.jsonl (append)
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from _axon_env import default_to_virtual_cpu, scrubbed_cpu_env  # noqa: E402

SCHEMA_VERSION = 1

#: The child request mix: same shapes every interpreter serves, so the
#: store-warm child's key set is exactly the store-cold child's.
_CHILD = """
import json, sys, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import dhqr_tpu
from dhqr_tpu.serve.cache import default_cache
from dhqr_tpu.serve.store import default_store

rng = np.random.default_rng(11)
A = jnp.asarray(rng.standard_normal((64, 32)), jnp.float32)
b = jnp.asarray(rng.standard_normal((64,)), jnp.float32)
A2 = jnp.asarray(rng.standard_normal((128, 32)), jnp.float32)
b2 = jnp.asarray(rng.standard_normal((128,)), jnp.float32)
t0 = time.perf_counter()
x = dhqr_tpu.batched_lstsq([A], [b])[0]
np.asarray(x)
first_request_s = time.perf_counter() - t0
dhqr_tpu.batched_lstsq([A2], [b2])
wall_s = time.perf_counter() - t0
store = default_store()
print(json.dumps({
    "first_request_s": first_request_s,
    "wall_s": wall_s,
    "cache": default_cache().stats(),
    "store": None if store is None else store.stats(),
}))
"""


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def _run_child(env: dict, tag: str) -> dict:
    with tempfile.NamedTemporaryFile("w", suffix=".py", dir=None,
                                     delete=False) as fh:
        fh.write(_CHILD)
        script = fh.name
    try:
        proc = subprocess.run([sys.executable, script], env=env, cwd=_REPO,
                              capture_output=True, text=True, timeout=300)
    finally:
        os.unlink(script)
    if proc.returncode != 0:
        raise RuntimeError(
            f"fleet child {tag} rc={proc.returncode}\n"
            f"stderr:{proc.stderr[-2000:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main() -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    rnd = int(os.environ.get("DHQR_ROUND", "22"))
    default_to_virtual_cpu(8)
    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np

    import dhqr_tpu
    from dhqr_tpu.serve.cache import ExecutableCache
    from dhqr_tpu.serve.errors import ReplicaLost, ServeError
    from dhqr_tpu.serve.router import Router
    from dhqr_tpu.serve.store import ExecutableStore
    from dhqr_tpu.utils.config import FleetConfig

    dev = jax.devices()[0]
    platform = dev.platform
    kind = getattr(dev, "device_kind", "?")
    out_path = os.path.join(_REPO, "benchmarks", "results",
                            f"serving_fleet_{platform}.jsonl")

    def emit(rec):
        rec.update(platform=platform, device_kind=kind, round=rnd,
                   schema_version=SCHEMA_VERSION)
        line = json.dumps(rec)
        print(line, flush=True)
        with open(out_path, "a") as f:
            f.write(line + "\n")

    # ------------------------------------------------ 1. cold-start ladder
    _stage("warmstart")
    with tempfile.TemporaryDirectory(prefix="dhqr-fleet-bench-") as root:
        store_dir = os.path.join(root, "store")
        children = {
            "nostore": _run_child(
                scrubbed_cpu_env(1, DHQR_FLEET_STORE=""), "nostore"),
            "store_cold": _run_child(
                scrubbed_cpu_env(1, DHQR_FLEET_STORE=store_dir),
                "store_cold"),
            "store_warm": _run_child(
                scrubbed_cpu_env(1, DHQR_FLEET_STORE=store_dir),
                "store_warm"),
        }
    warm = children["store_warm"]
    cold = children["store_cold"]
    warm_zero = (warm["cache"]["compile_seconds"] == 0
                 and warm["store"]["puts"] == 0
                 and warm["store"]["disk_hits"] >= 1
                 and warm["store"]["deserialize_failures"] == 0)
    # Wall ratio: the warm replica's first-request latency against the
    # compiling replica's (compile included — that is the point).
    wall_ratio = warm["first_request_s"] / max(cold["first_request_s"],
                                               1e-9)
    emit({"metric": "serving_fleet_warmstart",
          "warm_zero_compiles": bool(warm_zero),
          "warm_compile_seconds": warm["cache"]["compile_seconds"],
          "warm_disk_hits": warm["store"]["disk_hits"],
          "warm_first_request_s": round(warm["first_request_s"], 4),
          "cold_first_request_s": round(cold["first_request_s"], 4),
          "nostore_first_request_s": round(
              children["nostore"]["first_request_s"], 4),
          "warm_over_cold_wall": round(wall_ratio, 4)})

    # ------------------------------------------- 2. router capacity burst
    _stage("capacity")
    rng = np.random.default_rng(0)
    A = jnp.asarray(rng.standard_normal((64, 32)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((64,)), jnp.float32)
    dhqr_tpu.batched_lstsq([A], [b])  # compile outside the timed burst
    n_requests = 120

    def burst(submit):
        t0 = time.perf_counter()
        futs = [submit() for _ in range(n_requests)]
        for f in futs:
            f.result(timeout=120)
        return n_requests / (time.perf_counter() - t0)

    from dhqr_tpu.serve.scheduler import AsyncScheduler
    single = AsyncScheduler(workers=1)
    single_rps = burst(lambda: single.submit("lstsq", A, b, deadline=60.0))
    single.shutdown()
    router = Router(replicas=3, fleet=FleetConfig(replicas=3, failovers=1),
                    workers=1)
    fleet_rps = burst(lambda: router.submit("lstsq", A, b, deadline=60.0))
    emit({"metric": "serving_fleet_router", "phase": "capacity",
          "replicas": 3, "requests": n_requests,
          "single_requests_s": round(single_rps, 2),
          "fleet_requests_s": round(fleet_rps, 2),
          "fleet_over_single": round(fleet_rps / max(single_rps, 1e-9), 4)})

    # --------------------------------------------- 3. replica-kill ladder
    _stage("kill_ladder")
    x_ref = np.asarray(dhqr_tpu.batched_lstsq([A], [b])[0])
    outcomes = {"ok": 0, "lost": 0, "typed": 0, "untyped": 0}
    futs = []
    survivors_served = True
    for kill in (None, 0, 1):
        futs.extend(router.submit("lstsq", A, b, deadline=120.0)
                    for _ in range(20))
        if kill is not None:
            router.kill(kill)
            try:
                x = router.submit("lstsq", A, b,
                                  deadline=120.0).result(timeout=120)
                survivors_served &= bool(
                    np.allclose(np.asarray(x), x_ref, atol=1e-4))
            except Exception:
                survivors_served = False
    for f in futs:
        try:
            x = f.result(timeout=120)
            outcomes["ok" if np.allclose(np.asarray(x), x_ref, atol=1e-4)
                     else "untyped"] += 1
        except ReplicaLost:
            outcomes["lost"] += 1
        except ServeError:
            outcomes["typed"] += 1
        except BaseException:
            outcomes["untyped"] += 1
    snap = router.metrics_snapshot()
    router.shutdown()
    monotone = (outcomes["untyped"] == 0 and survivors_served
                and sum(outcomes.values()) == 60
                and snap["replicas_healthy"] == 1)
    emit({"metric": "serving_fleet_chaos", "replicas": 3, "killed": 2,
          "requests": 60, "monotone_typed": bool(monotone),
          "survivors_served": bool(survivors_served),
          "resolved_ok": outcomes["ok"], "resolved_lost": outcomes["lost"],
          "resolved_typed": outcomes["typed"],
          "resolved_untyped": outcomes["untyped"],
          "router_failovers": snap["failovers"]})

    # --------------------------------------------------- 4. store overhead
    _stage("warm_overhead")
    with tempfile.TemporaryDirectory(prefix="dhqr-fleet-ovh-") as root:
        key_args = ("lstsq", A, b)

        def warm_loop(cache):
            # Pay the compile, then time the warm path only.
            from dhqr_tpu.serve import engine as _engine

            _engine.batched_lstsq([A], [b], cache=cache)
            before = cache.stats()["compile_seconds"]
            n = 150
            t0 = time.perf_counter()
            for _ in range(n):
                _engine.batched_lstsq([A], [b], cache=cache)
            rps = n / (time.perf_counter() - t0)
            recompiled = cache.stats()["compile_seconds"] != before
            return rps, recompiled

        plain_rps, plain_rec = warm_loop(
            ExecutableCache(max_size=64, store=None))
        store_rps, store_rec = warm_loop(
            ExecutableCache(max_size=64,
                            store=ExecutableStore(os.path.join(root, "s"))))
        del key_args
    ratio = store_rps / max(plain_rps, 1e-9)
    emit({"metric": "serving_fleet", "phase": "warm_store",
          "nostore_requests_s": round(plain_rps, 2),
          "store_requests_s": round(store_rps, 2),
          "store_over_nostore": round(ratio, 4),
          "warm_recompiles": int(plain_rec) + int(store_rec)})

    # ------------------------------------------------------------ verdict
    ok = bool(warm_zero and monotone and ratio >= 0.95
              and not (plain_rec or store_rec))
    emit({"metric": "serving_fleet_verdict", "kind": "verdict",
          "value": round(ratio, 4),
          "unit": "warm store/nostore throughput ratio",
          "warm_zero_compiles": bool(warm_zero),
          "monotone_typed": bool(monotone),
          "store_overhead_in_bar": bool(ratio >= 0.95),
          "warm_recompiles": int(plain_rec) + int(store_rec),
          "ok": ok})
    _stage("done")
    if not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
