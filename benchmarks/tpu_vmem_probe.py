"""Round-3 session-3 TPU probe: VMEM residency limits + split-precision trade.

Two hardware questions, each stage one JSONL line on stdout:

1. **Single-copy VMEM residency** — ``pallas_panel_supported`` budgets TWO
   resident panel copies because the step body's ``at - W*v`` chain might
   materialize a second panel-sized value unless Mosaic fuses it
   (ops/pallas_panel.py). If the fused kernel actually compiles and runs at
   single-copy sizes — (8192, 256), (11264, 256), (16384, 128) are all
   ~8.4-11.5 MB one-copy but >16 MB two-copy — the gate can drop to one
   copy (``DHQR_PALLAS_PANEL_COPIES=1``), making 8192^2 nb=256 all-Pallas
   and 16384^2 nb=128 all-Pallas (both currently mixed XLA/Pallas).

2. **Split trailing precision** — ``trailing_precision="high"`` runs the
   trailing-update GEMMs (~all the flops) at 3 MXU passes instead of 6
   while panels/T-factors stay at "highest". All-"high" measured 4.4e-5
   backward error (fails the 1e-5 bar); if the failure is driven by the
   *panel* chains rather than the bulk GEMMs, the split passes the bar at
   ~half the dominant cost.

Run ONE instance at a time (the axon relay allows a single TPU process).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# Budget overrides (read per call by pallas_panel._gate_params) let the
# engine's internal gate admit every probed shape — hardware (Mosaic VMEM
# allocation) is the arbiter during this probe, not the planning model.
os.environ.setdefault("DHQR_PALLAS_PANEL_COPIES", "1")
os.environ.setdefault("DHQR_PALLAS_VMEM_BYTES", str(100 * 1024 * 1024))


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main() -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    from bench import _Watchdog

    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from dhqr_tpu.ops.blocked import _apply_q_impl, _blocked_qr_impl
    from dhqr_tpu.ops.householder import _householder_qr_impl
    from dhqr_tpu.ops.pallas_panel import _panel_qr_pallas_jit
    from dhqr_tpu.ops.solve import r_matrix
    from dhqr_tpu.utils.profiling import sync

    _stage("backend_init")
    with _Watchdog("backend_init", 150):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    rng = np.random.default_rng(0)

    def emit(rec):
        rec["platform"] = platform
        rec["device_kind"] = kind
        print(json.dumps(rec), flush=True)

    emit({"metric": "probe_start", "value": 1,
          "panel_copies": os.environ.get("DHQR_PALLAS_PANEL_COPIES")})

    # ---- 1. Single-copy panel residency: compile + run + verify vs XLA ----
    def panel_stage(m, nb, watchdog=240):
        name = f"panel_{m}x{nb}"
        _stage(name)
        try:
            with _Watchdog(name, watchdog):
                panel = jnp.asarray(rng.standard_normal((m, nb)), jnp.float32)
                sync(panel)
                t0 = time.perf_counter()
                comp = _panel_qr_pallas_jit.lower(
                    panel, 0, interpret=False).compile()
                compile_s = time.perf_counter() - t0
                pf, al = comp(panel, 0)
                sync(al)
                ts = []
                for _ in range(3):
                    t0 = time.perf_counter()
                    pf, al = comp(panel, 0)
                    sync(al)
                    ts.append(time.perf_counter() - t0)
                # Cheap structural verification (the kernel's numerics are
                # pinned by tests/test_pallas_panel.py; here the question is
                # residency): every reflector has ||v||^2 = 2, R diag in al.
                Y = jnp.tril(pf)
                vnorms = jnp.sum(Y * Y, axis=0)
                vdev = float(jnp.max(jnp.abs(vnorms - 2.0)))
                finite = bool(jnp.all(jnp.isfinite(pf)) &
                              jnp.all(jnp.isfinite(al)))
                emit({"metric": name, "ok": True,
                      "seconds": round(min(ts), 4),
                      "compile_seconds": round(compile_s, 2),
                      "max_vnorm_dev": vdev, "finite": finite})
                return finite and vdev < 1e-4
        except Exception as ex:
            emit({"metric": name, "ok": False,
                  "error": f"{type(ex).__name__}: {ex}"[:500]})
            return False

    # Device is a v5e ("TPU v5 lite") — VMEM is far larger than the generic
    # 16 MB planning number, so probe well past the old gate. Mosaic's
    # allocator is the arbiter; failures are caught and recorded per shape.
    ok_8192_256 = panel_stage(8192, 256)
    ok_16384_128 = panel_stage(16384, 128)
    ok_4096_512 = panel_stage(4096, 512)
    ok_16384_256 = panel_stage(16384, 256)
    ok_8192_512 = panel_stage(8192, 512)
    ok_16384_512 = panel_stage(16384, 512) if ok_8192_512 else False

    # ---- 2. Full QR chain timings with the relaxed gate ----
    def chain_time(n, nb, chain, watchdog, trailing=None, repeats=3,
                   backward_error=False, pallas=True):
        name = f"qr_{n}_nb{nb}" + ("_pallas" if pallas else "") + \
            (f"_trail_{trailing}" if trailing else "")
        _stage(name)
        try:
            with _Watchdog(name, watchdog):
                A = jnp.asarray(rng.random((n, n)), jnp.float32)
                sync(A)
                kw = dict(precision="highest", pallas=pallas, norm="fast",
                          panel_impl="loop", trailing_precision=trailing)
                t0 = time.perf_counter()
                single = _blocked_qr_impl.lower(A, nb, **kw).compile()
                H, al = single(A)
                sync(al)

                def chained(A):
                    def body(C, _):
                        Hc, ac = _blocked_qr_impl(C, nb, **kw)
                        return Hc, ac[0]
                    return lax.scan(body, A, None, length=chain)

                ck = jax.jit(chained).lower(A).compile()
                compile_s = time.perf_counter() - t0
                Hc, s = ck(A)
                sync(s)

                def tmin(f):
                    ts = []
                    for _ in range(repeats):
                        t0 = time.perf_counter()
                        r = f(A)
                        sync(r[1])
                        ts.append(time.perf_counter() - t0)
                    return min(ts)

                t1 = tmin(single)
                tk = tmin(ck)
                t = (tk - t1) / (chain - 1)
                unreliable = not (tk > t1 * 1.05 and t > 0)
                if unreliable:
                    t = t1
                flops = (4.0 / 3.0) * n**3
                rec = {"metric": f"qr_gflops_per_chip_f32_{n}x{n}",
                       "value": round(flops / t / 1e9, 2), "unit": "GFLOP/s",
                       "seconds": round(t, 4), "block_size": nb,
                       "pallas_panels": pallas, "chain_length": chain,
                       "trailing_precision": trailing,
                       "panel_copies_gate": 1,
                       "seconds_single_dispatch": round(t1, 4),
                       "seconds_chain": round(tk, 4),
                       "compile_seconds": round(compile_s, 2),
                       "chain_unreliable": unreliable}
                if backward_error:
                    QR = _apply_q_impl(H, r_matrix(H, al), nb,
                                       precision="highest")
                    rec[f"backward_error_{n}"] = float(
                        jnp.linalg.norm(QR - A) / jnp.linalg.norm(A))
                emit(rec)
        except Exception as ex:
            emit({"metric": name, "ok": False,
                  "error": f"{type(ex).__name__}: {ex}"[:500]})

    # Full-QR wins where the panel stages passed — likeliest headline
    # movers first. (The split-precision stages ran in probe v1: trailing=
    # "high" bought NOTHING — 9,777 vs 10,285 GFLOP/s, the trailing GEMMs
    # are HBM-bound not MXU-pass-bound — and fails the bar at 2.7e-5.)
    if ok_4096_512:
        chain_time(4096, 512, 25, 480)
    if ok_8192_256:
        chain_time(8192, 256, 5, 480)
    if ok_8192_512:
        chain_time(8192, 512, 5, 480)
    if ok_16384_256:
        chain_time(16384, 256, 3, 600, repeats=2)
    elif ok_16384_128:
        chain_time(16384, 128, 3, 560, repeats=2)
    if ok_16384_512:
        chain_time(16384, 512, 3, 600, repeats=2)
    _stage("done")


if __name__ == "__main__":
    main()
