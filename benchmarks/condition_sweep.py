"""Condition sweep: the round-13 chaos benchmark for the numeric ladder.

One cell per (cond, engine, policy): build a matrix with a geometric
singular-value ladder at the target condition number, run the GUARDED
least-squares path (``guards="full"`` — screening, breakdown detection,
the fallback ladder, AND the one-shot 8x-LAPACK residual probe), and
record what happened:

* ``outcome="ok"`` — some rung answered within the 8x criterion; the
  row carries the engine that answered, the escalation count, the
  probe's residual ratio, and an INDEPENDENT recomputation of the
  ratio (the "no silent garbage" cross-check — probe and recheck must
  agree on pass/fail);
* ``outcome=<typed error>`` — the ladder ran dry and refused typed
  (``Breakdown`` / ``IllConditioned`` / ``ResidualGateFailed``), with
  the condition estimate and the per-rung attempt record.

The acceptance invariant (benchmarks/README.md round-13 rules, pinned
by the verdict row): EVERY cell is ok-within-8x or typed — zero
silent-garbage cells — and re-running a sample of cells after the
sweep compiles NOTHING (the guards and every rung's engine impl are
shape-cached).

The policy axis per engine is the set the public ``lstsq`` accepts
there (a trailing split is a blocked-householder knob; tsqr takes no
refinement): householder runs accurate+fast, the cholqr family
accurate+refine, tsqr accurate.

CPU runs in float64 (the container pins x64 off-TPU), so the cond
ladder is meaningful to 1e14: the f64 CholeskyQR2 window is ~7e7, the
shifted form's ~5e14 — the ladder's engine transitions all happen
inside the sweep. A TPU replay runs f32 (window ~3e3) with the same
script; rows are platform-stamped.

Usage:  python benchmarks/condition_sweep.py [m n]   (default 192 24)
Writes: benchmarks/results/condition_sweep_<platform>.jsonl (append).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

CONDS = (1e2, 1e4, 1e6, 1e8, 1e10, 1e12, 1e14)

#: (engine, policy-spec or None) cells — the combinations the public
#: lstsq accepts per engine family (see module docstring).
ENGINE_POLICIES = (
    ("cholqr2", None),
    ("cholqr2", "highest/r1"),
    ("cholqr3", None),
    ("cholqr3", "highest/r1"),
    ("tsqr", None),
    ("householder", None),
    ("householder", "fast"),
)


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def _ill_conditioned(rng, m, n, cond, dtype):
    import numpy as np

    U, _ = np.linalg.qr(rng.standard_normal((m, n)))
    V, _ = np.linalg.qr(rng.standard_normal((n, n)))
    s = np.geomspace(1.0, 1.0 / cond, n)
    A = (U * s) @ V.T
    b = rng.standard_normal(m)
    return A.astype(dtype), b.astype(dtype)


def main(m: int = 192, n: int = 24) -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np

    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(_REPO, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception as e:
        print(f"# compile cache unavailable: {e}", file=sys.stderr)

    platform = jax.default_backend()
    if platform == "cpu":
        jax.config.update("jax_enable_x64", True)
        dtype = np.float64
    else:
        dtype = np.float32

    from dhqr_tpu.models.qr_model import _lstsq_impl
    from dhqr_tpu.numeric import NumericalError, guarded_lstsq
    from dhqr_tpu.numeric.guards import (
        _nonfinite_impl,
        _screen_impl,
        _screen_rhs_impl,
        residual_ratio,
    )
    from dhqr_tpu.ops.cholqr import _cholqr_lstsq_impl, cholqr_max_cond
    from dhqr_tpu.ops.tsqr import _tsqr_lstsq_impl
    from dhqr_tpu.utils.testing import TOLERANCE_FACTOR

    def compiles():
        return sum(f._cache_size() for f in
                   (_lstsq_impl, _cholqr_lstsq_impl, _tsqr_lstsq_impl,
                    _screen_impl, _screen_rhs_impl, _nonfinite_impl))

    out_path = os.path.join(_REPO, "benchmarks", "results",
                            f"condition_sweep_{platform}.jsonl")
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    fh = open(out_path, "a", buffering=1)

    def emit(row):
        from bench import SCHEMA_VERSION

        row = {"round": 13, "platform": platform, "ts": round(time.time(), 1),
               "schema_version": SCHEMA_VERSION, **row}
        line = json.dumps(row)
        print(line, flush=True)
        fh.write(line + "\n")

    emit({"kind": "meta", "m": m, "n": n, "dtype": np.dtype(dtype).name,
          "conds": list(CONDS),
          "cells": [f"{e}+{p or 'accurate'}" for e, p in ENGINE_POLICIES],
          "windows": {"cholqr2": cholqr_max_cond(dtype),
                      "cholqr3": cholqr_max_cond(dtype, shift=True)}})

    rng = np.random.default_rng(13)
    total = ok_cells = typed_cells = garbage_cells = 0
    fallback_depth_max = 0
    _stage("sweep")
    for cond in CONDS:
        A_np, b_np = _ill_conditioned(rng, m, n, cond, dtype)
        A, b = jnp.asarray(A_np), jnp.asarray(b_np)
        for engine, policy in ENGINE_POLICIES:
            total += 1
            cell = {"kind": "cell", "cond": cond, "engine": engine,
                    "policy": policy or "accurate"}
            t0 = time.perf_counter()
            try:
                res = guarded_lstsq(A, b, engine=engine, policy=policy,
                                    guards="full")
            except NumericalError as e:
                typed_cells += 1
                emit({**cell, "outcome": type(e).__name__,
                      "cond_estimate": e.cond_estimate,
                      "attempts": [
                          {"engine": a.engine, "policy": a.policy,
                           "outcome": a.outcome}
                          for a in e.attempts],
                      "seconds": round(time.perf_counter() - t0, 4)})
                continue
            seconds = time.perf_counter() - t0
            # Independent recheck: the probe already gated at 8x; a
            # disagreement here would BE the silent-garbage bug.
            recheck = residual_ratio(A_np, b_np, np.asarray(res.x))
            silent = recheck > TOLERANCE_FACTOR
            garbage_cells += int(silent)
            ok_cells += 1 - int(silent)
            fallback_depth_max = max(fallback_depth_max, res.escalations)
            emit({**cell, "outcome": "ok" if not silent else "GARBAGE",
                  "engine_used": res.engine,
                  "policy_used": res.attempts[-1].policy,
                  "escalations": res.escalations,
                  "path": [a.outcome for a in res.attempts],
                  "residual_ratio": round(res.residual_ratio, 4),
                  "recheck_ratio": round(recheck, 4),
                  "seconds": round(seconds, 4)})

    # Degenerate cells: a structurally singular input (zero column,
    # cond = inf) and a NaN-poisoned input — the rows that MUST fail
    # typed on every route (no ladder depth can answer them). These
    # are the artifact's typed-refusal evidence.
    _stage("degenerate")
    A_np, b_np = _ill_conditioned(rng, m, n, 1e2, dtype)
    degenerate = (
        ("zero_column",
         jnp.asarray(A_np).at[:, n // 2].set(0.0), jnp.asarray(b_np)),
        ("nan_input",
         jnp.asarray(A_np).at[0, 0].set(jnp.nan), jnp.asarray(b_np)),
    )
    for label, A, b in degenerate:
        for engine, policy in ENGINE_POLICIES:
            total += 1
            cell = {"kind": "cell", "cond": label, "engine": engine,
                    "policy": policy or "accurate"}
            t0 = time.perf_counter()
            try:
                res = guarded_lstsq(A, b, engine=engine, policy=policy,
                                    guards="full")
            except NumericalError as e:
                typed_cells += 1
                emit({**cell, "outcome": type(e).__name__,
                      "cond_estimate": e.cond_estimate,
                      "seconds": round(time.perf_counter() - t0, 4)})
                continue
            garbage_cells += 1  # a degenerate input must never "succeed"
            emit({**cell, "outcome": "GARBAGE",
                  "engine_used": res.engine,
                  "seconds": round(time.perf_counter() - t0, 4)})

    # Warm-repeat pin: replay one representative cell per engine; the
    # sweep already compiled every program, so this must add ZERO.
    _stage("warm_repeat")
    n_compiled = compiles()
    A_np, b_np = _ill_conditioned(rng, m, n, 1e4, dtype)
    A, b = jnp.asarray(A_np), jnp.asarray(b_np)
    for engine, policy in ENGINE_POLICIES:
        guarded_lstsq(A, b, engine=engine, policy=policy, guards="full")
    warm_recompiles = compiles() - n_compiled

    verdict = {
        "kind": "verdict", "cells": total, "ok_within_8x": ok_cells,
        "typed_failures": typed_cells, "silent_garbage": garbage_cells,
        "max_fallback_depth": fallback_depth_max,
        "warm_repeat_recompiles": warm_recompiles,
        "no_silent_garbage": garbage_cells == 0,
        "every_cell_ok_or_typed": ok_cells + typed_cells == total
        and garbage_cells == 0,
        "zero_recompiles_warm": warm_recompiles == 0,
    }
    emit(verdict)
    fh.close()
    if not (verdict["every_cell_ok_or_typed"]
            and verdict["zero_recompiles_warm"]):
        sys.exit(1)


if __name__ == "__main__":
    main(*(int(a) for a in sys.argv[1:3]))
