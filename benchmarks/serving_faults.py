"""Chaos ladder: the async serving tier under a seeded fault schedule.

The round-12 tentpole's decision artifact. The SAME seeded Poisson×Zipf
open-loop load generator as benchmarks/serving_async.py (round 11), run
four times at one offered rate near saturation — fault-free baseline,
light injected faults, heavy injected faults, and recovery (faults
disarmed again) — plus a worker-crash segment and a quarantine segment:

* ``open_loop_baseline`` — no faults armed: the PR-6 behavior (and the
  number recovery is judged against);
* ``open_loop_faults_light`` / ``..._heavy`` — ``dhqr_tpu.faults``
  armed on the ``serve.dispatch`` (transient dispatch failures, retried
  with backoff / bisected) and ``serve.latency`` (injected dispatch
  latency) sites at two seeded intensities: throughput must DEGRADE
  MONOTONICALLY with the injected fault rate, and every accepted
  request's future must still resolve — success or typed ServeError,
  no hang, no lost request (THE chaos invariant, also pinned by
  tests/test_faults.py);
* ``open_loop_recovery`` — harness disarmed: throughput must return to
  >= 0.9x the fault-free baseline and the steady state must be
  ZERO-recompile again (cache misses flat across the phase) — chaos
  must leave no residue;
* ``worker_crashes`` — ``serve.worker`` armed for exactly 2 crashes
  against the live dispatcher pool: both crashes detected + respawned,
  the stream still completes;
* ``quarantine`` — a fresh cache with one injected compile failure: the
  poison bucket fails typed (CompileFailed, then Quarantined inside the
  cooldown — exactly ONE compile attempt), and after expiry the same
  key compiles clean and serves warm.

Acceptance (ISSUE 7): every submitted future resolves typed under every
schedule; rps(heavy) <= rps(light) <= rps(baseline) within noise;
recovery >= 0.9x baseline with 0 recompiles; quarantine caps the poison
bucket at one compile per cooldown.

Usage:  python benchmarks/serving_faults.py [n_requests] [rate_frac]
Writes: benchmarks/results/serving_faults_<platform>.jsonl (append).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# The round-8/11 shape ladder verbatim: the chaos numbers stay
# comparable to the serving_async artifact.
SHAPE_LADDER = [
    (64, 16), (100, 36), (128, 48), (192, 64),
    (250, 100), (384, 128), (500, 180), (640, 256),
]
MICRO_BATCH = 32
SLO_MS = 2000.0           # generous: faults should surface as retries
                          # and degraded throughput, not deadline kills
# Shorter than round-11's 300 ms on purpose: the chaos ladder wants MANY
# dispatches per phase (every dispatch is a fault-site visit), and the
# degradation metric is end-to-end throughput rather than SLO-shaped
# in-window completions, so coalescing breadth matters less here.
FLUSH_INTERVAL_MS = 100.0

# The two seeded fault intensities. Aggressive on purpose: on this
# shared CPU the run-to-run throughput noise is +-10-20%, so the
# injected degradation must be far larger to make the monotonicity
# check meaningful.
LIGHT_FAULTS = dict(dispatch_p=0.15, latency_p=0.40, latency_ms=40.0)
HEAVY_FAULTS = dict(dispatch_p=0.35, latency_p=0.70, latency_ms=80.0)


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main(n_requests: int = 384, rate_frac: float = 0.90) -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    from bench import SCHEMA_VERSION, ROUND, _Watchdog

    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np

    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(_REPO, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

    from dhqr_tpu import faults
    from dhqr_tpu.serve import AsyncScheduler, ServeError, prewarm
    from dhqr_tpu.serve.cache import ExecutableCache
    from dhqr_tpu.serve.errors import CompileFailed, Quarantined
    from dhqr_tpu.utils.config import (FaultConfig, SchedulerConfig,
                                       ServeConfig)
    from dhqr_tpu.utils.profiling import LatencyHistogram, sync

    _stage("backend_init")
    with _Watchdog("backend_init", 240):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    out_path = os.path.join(_REPO, "benchmarks", "results",
                            f"serving_faults_{platform}.jsonl")

    def emit(rec):
        rec.update(platform=platform, device_kind=kind, round=ROUND,
                   schema_version=SCHEMA_VERSION)
        line = json.dumps(rec)
        print(line, flush=True)
        with open(out_path, "a") as f:
            f.write(line + "\n")

    # ---- the request stream (fixed seeds: artifact is reproducible) ----
    rng = np.random.default_rng(0)
    ranks = np.arange(len(SHAPE_LADDER))
    weights = 1.0 / (ranks + 1.0) ** 1.1
    weights /= weights.sum()
    picks = rng.choice(len(SHAPE_LADDER), size=n_requests, p=weights)
    shapes = [SHAPE_LADDER[i] for i in picks]
    As = [jnp.asarray(rng.random(s), jnp.float32) for s in shapes]
    bs = [jnp.asarray(rng.random(s[0]), jnp.float32) for s in shapes]
    sync(As[-1])
    scfg = ServeConfig(max_batch=MICRO_BATCH)
    arrivals = None  # filled after capacity is measured

    _stage("prewarm")
    with _Watchdog("prewarm", 2400):
        acache = ExecutableCache(max_size=64)
        pow2 = [1 << i for i in range((MICRO_BATCH - 1).bit_length() + 1)
                if 1 << i <= MICRO_BATCH]
        keys = prewarm([(c, m, n) for (m, n) in SHAPE_LADDER for c in pow2],
                       serve_config=scfg, cache=acache)
    emit({"metric": "serving_faults", "phase": "prewarm",
          "keys": len(keys), "cache": acache.stats()})

    # ---- capacity probe: sets the open-loop operating point ------------
    _stage("capacity")
    with _Watchdog("capacity", 1800):
        cap_sched = AsyncScheduler(
            serve_config=scfg,
            sched_config=SchedulerConfig(slo_ms=60e3, queue_depth=16384,
                                         flush_interval_ms=FLUSH_INTERVAL_MS),
            cache=acache, start=False)
        drain_s = 0.0
        for _ in range(2):
            futs = [cap_sched.submit("lstsq", A, b, deadline=60.0)
                    for A, b in zip(As, bs)]
            t0 = time.perf_counter()
            cap_sched.drain()
            drain_s += time.perf_counter() - t0
            assert all(f.done() for f in futs)
        capacity_rps = 2 * n_requests / drain_s
        cap_sched.shutdown()
    emit({"metric": "serving_faults", "phase": "capacity",
          "requests_per_s": round(capacity_rps, 1)})
    offered_rps = rate_frac * capacity_rps
    inter = np.random.default_rng(1).exponential(
        1.0 / offered_rps, size=n_requests)
    arrivals = np.cumsum(inter)

    # ---- one open-loop pass (shared by all four phases) ----------------
    def open_loop(phase, fault_cfg=None):
        """Poisson open loop at the fixed offered rate; returns the
        phase record. The SAME arrival schedule every phase, so the
        only variable across phases is the armed fault schedule. The
        phase's throughput number is END-TO-END (first submit -> last
        completion): on a seconds-long stream it is the measure that
        actually moves with injected latency and retry work, where
        in-window completions quantize to the offered rate."""
        lat = LatencyHistogram()
        sched = AsyncScheduler(
            serve_config=scfg,
            sched_config=SchedulerConfig(slo_ms=SLO_MS, queue_depth=4096,
                                         flush_interval_ms=FLUSH_INTERVAL_MS),
            cache=acache)
        futs, done_at = [None] * n_requests, [0.0] * n_requests

        def run_stream():
            t_start = time.perf_counter()
            rejected = 0
            for i in range(n_requests):
                delay = t_start + arrivals[i] - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                t_submit = time.perf_counter()
                try:
                    fut = sched.submit("lstsq", As[i], bs[i],
                                       deadline=SLO_MS / 1e3,
                                       tenant=f"t{picks[i]}")
                except ServeError:
                    rejected += 1
                    continue

                def cb(f, i=i, t=t_submit):
                    done_at[i] = time.perf_counter()
                    lat.record(done_at[i] - t)

                fut.add_done_callback(cb)
                futs[i] = fut
            return t_start, rejected

        misses0 = acache.stats()["misses"]
        harness = faults.install(fault_cfg) if fault_cfg else None
        try:
            t_start, rejected = run_stream()
            # THE chaos invariant: every ACCEPTED future resolves.
            from concurrent.futures import wait as _wait
            accepted = [f for f in futs if f is not None]
            _wait(accepted, timeout=600)
            assert all(f.done() for f in accepted), \
                f"{phase}: futures hung under the fault schedule"
        finally:
            if fault_cfg:
                faults.uninstall()
        sched_stats = sched.stats()
        sched.shutdown()
        typed_failures = 0
        for f in accepted:
            exc = f.exception()
            if exc is not None:
                assert isinstance(exc, ServeError), exc
                typed_failures += 1
        t_arr_end = t_start + arrivals[-1]
        in_window = sum(1 for d in done_at if 0.0 < d <= t_arr_end)
        t_last = max((d for d in done_at if d), default=t_start)
        ete_rps = len(accepted) / max(t_last - t_start, 1e-9)
        rec = {
            "metric": "serving_faults", "phase": phase,
            "requests": n_requests, "rejected": rejected,
            "offered_rps": round(offered_rps, 1),
            "end_to_end_rps": round(ete_rps, 1),
            "in_window_rps": round(in_window / arrivals[-1], 1),
            "typed_failures": typed_failures,
            "all_accepted_resolved": all(f.done() for f in accepted),
            "recompiles": acache.stats()["misses"] - misses0,
            "client_latency": lat.snapshot(),
            "scheduler": {k: sched_stats[k] for k in (
                "completed", "failed", "rejected", "rejected_unmeetable",
                "retries", "bisections", "poisoned", "flush_failures",
                "worker_crashes", "deadline_misses", "dispatches")},
        }
        if harness is not None:
            rec["injected"] = harness.stats()
        emit(rec)
        return rec

    def fault_config(p):
        return FaultConfig(
            sites=(("serve.dispatch", p["dispatch_p"], None),
                   ("serve.latency", p["latency_p"], None)),
            seed=7, latency_ms=p["latency_ms"])

    # Untimed warm stream first: the first threaded pass pays one-time
    # costs (thread-pool startup, executable first-touch) that would
    # land entirely on the baseline and flatter every later phase.
    _stage("open_loop_warmup")
    with _Watchdog("open_loop_warmup", 2400):
        open_loop("open_loop_warmup")
    _stage("open_loop_baseline")
    with _Watchdog("open_loop_baseline", 2400):
        base = open_loop("open_loop_baseline")
    _stage("open_loop_faults_light")
    with _Watchdog("open_loop_faults_light", 2400):
        light = open_loop("open_loop_faults_light",
                          fault_config(LIGHT_FAULTS))
    _stage("open_loop_faults_heavy")
    with _Watchdog("open_loop_faults_heavy", 2400):
        heavy = open_loop("open_loop_faults_heavy",
                          fault_config(HEAVY_FAULTS))
    _stage("open_loop_recovery")
    with _Watchdog("open_loop_recovery", 2400):
        recov = open_loop("open_loop_recovery")

    # ---- worker-crash segment ------------------------------------------
    _stage("worker_crashes")
    with _Watchdog("worker_crashes", 1200):
        wcfg = FaultConfig(sites=(("serve.worker", 1.0, 2),), seed=0)
        wsched = AsyncScheduler(
            serve_config=scfg, cache=acache, workers=2,
            sched_config=SchedulerConfig(slo_ms=60e3,
                                         flush_interval_ms=50.0))
        with faults.injected(wcfg) as wharness:
            wfuts = [wsched.submit("lstsq", As[i], bs[i], deadline=60.0)
                     for i in range(min(64, n_requests))]
            for f in wfuts:
                f.result(timeout=120)
        wstats = wsched.stats()
        alive = sum(t.is_alive() for t in wsched._threads)
        wsched.shutdown()
    emit({"metric": "serving_faults", "phase": "worker_crashes",
          "requests": len(wfuts), "injected": wharness.stats(),
          "worker_crashes": wstats["worker_crashes"],
          "workers_alive_after": alive,
          "completed": wstats["completed"]})

    # ---- quarantine segment --------------------------------------------
    _stage("quarantine")
    with _Watchdog("quarantine", 1200):
        qcache = ExecutableCache(max_size=8, quarantine_s=2.0)
        qcfg = FaultConfig(sites=(("serve.compile", 1.0, 1),), seed=0)
        from dhqr_tpu.serve import batched_lstsq
        qA, qb = As[0], bs[0]
        outcomes = []
        with faults.injected(qcfg):
            for _ in range(3):      # poison bucket stays hot...
                try:
                    batched_lstsq([qA], [qb], serve_config=scfg,
                                  cache=qcache)
                    outcomes.append("ok")
                except CompileFailed:
                    outcomes.append("compile_failed")
                except Quarantined:
                    outcomes.append("quarantined")
        time.sleep(2.1)             # ...cooldown expires...
        x = batched_lstsq([qA], [qb], serve_config=scfg, cache=qcache)[0]
        assert x.shape == (qA.shape[1],)
        qstats = qcache.stats()
    emit({"metric": "serving_faults", "phase": "quarantine",
          "outcomes": outcomes, "cache": qstats})

    # ---- verdict -------------------------------------------------------
    rps = [base["end_to_end_rps"], light["end_to_end_rps"],
           heavy["end_to_end_rps"], recov["end_to_end_rps"]]
    noise = 1.05                     # shared-CPU run-to-run tolerance
    monotone = rps[1] <= rps[0] * noise and rps[2] <= rps[1] * noise \
        and rps[2] < rps[0]
    recovered = rps[3] >= 0.9 * rps[0]
    resolved = all(r["all_accepted_resolved"]
                   for r in (base, light, heavy, recov))
    quarantine_ok = (outcomes == ["compile_failed", "quarantined",
                                  "quarantined"]
                     and qstats["compile_failures"] == 1)
    ok = (monotone and recovered and resolved
          and recov["recompiles"] == 0 and base["typed_failures"] == 0
          and recov["typed_failures"] == 0
          and wstats["worker_crashes"] == 2 and alive >= 2
          and quarantine_ok)
    emit({"metric": "serving_faults_verdict",
          "baseline_rps": rps[0], "faults_light_rps": rps[1],
          "faults_heavy_rps": rps[2], "recovery_rps": rps[3],
          "degradation_light": round(rps[1] / rps[0], 3),
          "degradation_heavy": round(rps[2] / rps[0], 3),
          "recovery_fraction_of_baseline": round(rps[3] / rps[0], 3),
          "throughput_monotone_in_fault_rate": bool(monotone),
          "recovered_to_0p9x": bool(recovered),
          "every_accepted_future_resolved": bool(resolved),
          "zero_recompiles_after_recovery": recov["recompiles"] == 0,
          "worker_crashes_respawned": wstats["worker_crashes"],
          "quarantine_single_compile": bool(quarantine_ok),
          "ok": bool(ok)})
    _stage("done")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 384,
         float(sys.argv[2]) if len(sys.argv) > 2 else 0.90)
