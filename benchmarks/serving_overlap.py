"""dhqr-pipeline acceptance: depth-k double-buffered panel broadcast.

The round-23 decision artifact (benchmarks/README "Round-23 decision
rules"): the pipelined blocked engine x CPU topology P in {2, 4, 8} x
comms wire rung in {f32, bf16} x overlap depth in {2, 4},

1. **traced program order** — the dhqr-audit order walk
   (``analysis.comms_pass.overlap_distance``) on an unrolled-tier
   shape must show panel q+k's broadcast psum issued BEFORE panel q's
   wide trailing GEMM at depth k (distance >= k; the lookahead
   baseline reads exactly 1, the classic schedule 0). Audited at
   P in {2, 4}: the walk needs an unrolled trace (panels <= 8) whose
   shard-local trailing width exceeds nb, which no P = 8 shape can
   satisfy — and the issue order is topology-independent anyway (the
   same program at a wider shard);
2. **collective census** — the traced psum launch count at every
   depth is IDENTICAL to the one-panel lookahead it generalizes, and
   the traced byte volume stays within the unchanged DHQR302 budget
   slack (the ring re-broadcasts nothing: the only delta is the
   delayed trailing frame, <= depth*nb extra rows of R per psum); the
   depth-2 bf16 wire rung must still cut traced bytes >= 1.5x vs its
   f32 twin (contract slack 1.3 machine-enforces 1.53x statically);
3. **bit identity** — the depth-k factorization is bitwise equal to
   the lookahead schedule at every topology, both unrolled and scan
   tiers: identical per-column arithmetic is the design invariant,
   so ``accurate`` keeps its reproducibility story at any depth;
4. **accuracy** — a real pipelined solve per cell, normal-equations
   residual within the reference 8x-LAPACK criterion (the bf16 rung
   through the model tier, whose compressed path carries CSNE
   recovery by contract);
5. **zero warm recompiles** — each (depth, comms) mode compiles once;
   warm repeats count zero ``backend_compile`` events;
6. **armed overhead** — a warm pipelined dispatch loop under the
   armed pulse store holds >= 0.95x the disarmed rate (capture-once
   per label; the pipeline introduces no new capture points).

Ends with a ``serving_overlap_verdict`` row the regress gate's
``overlap-*`` rules enforce from then on.

Usage:  python benchmarks/serving_overlap.py
Writes: benchmarks/results/serving_overlap_<platform>.jsonl (append)
"""

from __future__ import annotations

import json
import os
import signal
import statistics
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

DEVICE_COUNTS = (2, 4, 8)
DEPTHS = (2, 4)
#: Traced-volume ceiling vs the lookahead baseline: the ring's only
#: byte delta is the delayed trailing frame (<= depth*nb extra rows of
#: R per pf psum), measured 1.06-1.14x at these shapes — 1.25 catches
#: a schedule that starts re-broadcasting panels while staying clear
#: of frame-shape jitter. The DHQR302 gate enforces the same budget
#: statically with the standard 1.5 contract slack.
VOLUME_CEILING = 1.25
#: bf16 pipeline rung: contract slack 1.3 enforces 4 B / (2 B * 1.3)
#: = 1.53x statically; the artifact bar is 1.5 to the same effect.
WIRE_BAR = 1.5
WARM_DISPATCHES = 20
WARM_REPEATS = 6


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def _audit_n(P: int) -> int:
    """Unrolled-tier order-audit width: panels = n/4 must sit in
    [depth+1, MAX_UNROLLED_PANELS] so depth 4 is not clamped and the
    order walk sees every panel spelled out (scan bodies are traced
    once, hiding the cross-iteration issue order)."""
    return 24 if P <= 4 else 32


def main() -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    rnd = int(os.environ.get("DHQR_ROUND", "23"))
    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import monitoring

    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(_REPO, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

    from bench import SCHEMA_VERSION, _Watchdog

    compiles = {"n": 0}
    monitoring.register_event_duration_secs_listener(
        lambda name, *a, **k: compiles.__setitem__("n", compiles["n"] + 1)
        if name == "/jax/core/compile/backend_compile_duration" else None)

    from dhqr_tpu.analysis.comms_pass import collect_comms, overlap_distance
    from dhqr_tpu.models.qr_model import lstsq as model_lstsq
    from dhqr_tpu.obs import pulse as pulse_mod
    from dhqr_tpu.parallel.mesh import column_mesh
    from dhqr_tpu.parallel.sharded_qr import sharded_blocked_qr
    from dhqr_tpu.parallel.sharded_solve import sharded_lstsq
    from dhqr_tpu.utils.profiling import sync
    from dhqr_tpu.utils.testing import (
        TOLERANCE_FACTOR,
        normal_equations_residual,
        oracle_residual,
    )

    _stage("backend_init")
    with _Watchdog("backend_init", 240):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    out_path = os.path.join(_REPO, "benchmarks", "results",
                            f"serving_overlap_{platform}.jsonl")
    navail = len(jax.devices())
    counts = tuple(p for p in DEVICE_COUNTS if p <= navail)
    if not counts:
        print("serving_overlap: SKIPPED (needs >= 2 devices; set XLA_FLAGS="
              "--xla_force_host_platform_device_count=8 before the first "
              "backend touch — overlap_depth is mesh-only)",
              file=sys.stderr, flush=True)
        return

    def emit(rec):
        rec.update(platform=platform, device_kind=kind, round=rnd,
                   schema_version=SCHEMA_VERSION)
        line = json.dumps(rec)
        print(line, flush=True)
        with open(out_path, "a") as f:
            f.write(line + "\n")

    rng = np.random.default_rng(0)

    def problems(P):
        """Per-topology shapes: the serving shape n = 8P spans both
        schedule tiers (unrolled at P = 2, scan at P = 8); the audit
        shape stays unrolled so the order walk can read it."""
        n, nb = 8 * P, 4
        m = 2 * n
        n_aud = _audit_n(P)
        cmesh = column_mesh(P)
        A = jnp.asarray(rng.random((m, n)), jnp.float32)
        b = jnp.asarray(rng.random(m), jnp.float32)
        A_aud = jnp.asarray(rng.random((2 * n_aud, n_aud)), jnp.float32)
        return dict(P=P, n=n, nb=nb, m=m, cmesh=cmesh, A=A, b=b,
                    A_aud=A_aud)

    def qr_trace(ctx, A, depth, comms=None):
        return jax.make_jaxpr(
            lambda A_: sharded_blocked_qr(
                A_, ctx["cmesh"], block_size=ctx["nb"], lookahead=True,
                overlap_depth=depth, comms=comms))(A)

    # ---- phase 1: traced program order -----------------------------------
    # Audit topologies: an unrolled-tier audit needs panels = n/nb <=
    # MAX_UNROLLED_PANELS (scan bodies hide the cross-iteration order)
    # AND a shard-local trailing width wider than nb (the order walk
    # dates trailing GEMMs by their > nb output dim) — at P = 8 the two
    # conflict (n <= 8*nb forces local cols <= nb), and the issue order
    # is topology-independent (the same program at a wider shard), so
    # P in {2, 4} is the audit set.
    _stage("traced_order")
    order_ok = True
    with _Watchdog("traced_order", 1800):
        for P in [p for p in counts if p <= 4]:
            ctx = problems(P)
            # Baselines for the row's context: classic issues nothing
            # early (distance 0), lookahead exactly one panel.
            base = {}
            for name, kw in (("classic", {}), ("lookahead",
                                               dict(lookahead=True))):
                closed = jax.make_jaxpr(
                    lambda A_: sharded_blocked_qr(
                        A_, ctx["cmesh"], block_size=ctx["nb"], **kw)
                )(ctx["A_aud"])
                base[name] = overlap_distance(closed, ctx["nb"])
            for depth in DEPTHS:
                dist = overlap_distance(
                    qr_trace(ctx, ctx["A_aud"], depth), ctx["nb"])
                meets = dist is not None and dist >= depth
                order_ok = order_ok and meets
                emit({
                    "metric": "serving_overlap_order",
                    "engine": "blocked_qr", "devices": P, "depth": depth,
                    "value": dist,
                    "unit": "panels between broadcast psum and the wide "
                            "trailing GEMM it overtakes (traced order)",
                    "audit_n": _audit_n(P),
                    "classic_distance": base["classic"],
                    "lookahead_distance": base["lookahead"],
                    "meets_depth": bool(meets),
                })

    # ---- phase 2: collective census (launches + volume) ------------------
    _stage("census")
    census_ok = True
    wire_ok = True
    with _Watchdog("census", 1800):
        for P in counts:
            ctx = problems(P)
            la = collect_comms(qr_trace(ctx, ctx["A"], None))
            la_launch, la_vol = la.launches(), la.total_volume_bytes()
            for depth in DEPTHS:
                st = collect_comms(qr_trace(ctx, ctx["A"], depth))
                launches = st.launches()
                vol = st.total_volume_bytes()
                ratio = vol / max(la_vol, 1)
                same = launches == la_launch
                inside = ratio <= VOLUME_CEILING
                census_ok = census_ok and same and inside
                emit({
                    "metric": "serving_overlap_census",
                    "engine": "blocked_qr", "devices": P, "depth": depth,
                    "value": round(ratio, 4),
                    "unit": "pipelined traced bytes / lookahead traced "
                            "bytes (launch count must be identical)",
                    "launches": launches, "launches_lookahead": la_launch,
                    "launches_identical": bool(same),
                    "traced_bytes": vol, "traced_bytes_lookahead": la_vol,
                    "volume_ceiling": VOLUME_CEILING,
                    "volume_within_ceiling": bool(inside),
                })
            # The compressed rung: depth-2 bf16 vs depth-2 f32.
            vol_f32 = collect_comms(qr_trace(ctx, ctx["A"],
                                             2)).total_volume_bytes()
            vol_bf16 = collect_comms(qr_trace(ctx, ctx["A"], 2,
                                              "bf16")).total_volume_bytes()
            wratio = vol_f32 / max(vol_bf16, 1)
            wire_ok = wire_ok and wratio >= WIRE_BAR
            emit({
                "metric": "serving_overlap_wire",
                "engine": "blocked_qr", "devices": P, "depth": 2,
                "comms": "bf16",
                "value": round(wratio, 4),
                "unit": "f32 pipelined traced bytes / bf16 pipelined "
                        "traced bytes",
                "traced_bytes_f32": vol_f32,
                "traced_bytes_bf16": vol_bf16,
                "wire_bar": WIRE_BAR,
            })

    # ---- phase 3: depth-k is bit-identical to lookahead ------------------
    _stage("bit_identity")
    bit_identical = True
    with _Watchdog("bit_identity", 1800):
        for P in counts:
            ctx = problems(P)
            Hl, al = sharded_blocked_qr(ctx["A"], ctx["cmesh"],
                                        block_size=ctx["nb"],
                                        lookahead=True)
            for depth in DEPTHS:
                Hp, ap = sharded_blocked_qr(ctx["A"], ctx["cmesh"],
                                            block_size=ctx["nb"],
                                            lookahead=True,
                                            overlap_depth=depth)
                same = (np.array_equal(np.asarray(Hl), np.asarray(Hp))
                        and np.array_equal(np.asarray(al), np.asarray(ap)))
                bit_identical = bit_identical and same
                emit({"metric": "serving_overlap_bit_identity",
                      "devices": P, "depth": depth,
                      "pipeline_equals_lookahead": bool(same)})

    # ---- phase 4: accuracy across the matrix -----------------------------
    _stage("residuals")
    worst = 0.0
    cells = gated = 0
    with _Watchdog("residuals", 2400):
        for P in counts:
            ctx = problems(P)
            ref = oracle_residual(np.asarray(ctx["A"]),
                                  np.asarray(ctx["b"]))
            for depth in DEPTHS:
                for comms in (None, "bf16"):
                    if comms is None:
                        x = sharded_lstsq(ctx["A"], ctx["b"], ctx["cmesh"],
                                          block_size=ctx["nb"],
                                          lookahead=True,
                                          overlap_depth=depth)
                    else:
                        # The model tier carries the compressed-mode
                        # CSNE recovery contract.
                        x = model_lstsq(ctx["A"], ctx["b"],
                                        mesh=ctx["cmesh"],
                                        block_size=ctx["nb"],
                                        lookahead=True,
                                        overlap_depth=depth, comms=comms)
                    res = normal_equations_residual(
                        ctx["A"], np.asarray(x), ctx["b"])
                    ratio = res / ref if ref > 0 else float(res > 0)
                    cells += 1
                    gated += ratio < TOLERANCE_FACTOR
                    worst = max(worst, ratio)
                    emit({
                        "metric": "serving_overlap_residual",
                        "engine": "blocked_qr", "devices": P,
                        "depth": depth, "comms": comms or "f32",
                        "value": round(ratio, 4),
                        "unit": "normal-equations residual / LAPACK "
                                "oracle",
                        "residual_criterion": TOLERANCE_FACTOR,
                        "within_8x": bool(ratio < TOLERANCE_FACTOR),
                    })

    # ---- phase 5: zero warm recompiles per (depth, comms) mode -----------
    _stage("warm_recompiles")
    warm_recompiles = 0
    with _Watchdog("warm_recompiles", 1800):
        for P in counts:
            ctx = problems(P)
            for depth in DEPTHS:
                for comms in (None, "bf16"):
                    sync(sharded_blocked_qr(ctx["A"], ctx["cmesh"],
                                            block_size=ctx["nb"],
                                            lookahead=True,
                                            overlap_depth=depth,
                                            comms=comms))
                    before = compiles["n"]
                    sync(sharded_blocked_qr(ctx["A"], ctx["cmesh"],
                                            block_size=ctx["nb"],
                                            lookahead=True,
                                            overlap_depth=depth,
                                            comms=comms))
                    delta = compiles["n"] - before
                    warm_recompiles += delta
                    emit({"metric": "serving_overlap_recompiles",
                          "devices": P, "depth": depth,
                          "comms": comms or "f32",
                          "warm_recompiles": delta})

    # ---- phase 6: armed pulse overhead on warm pipelined dispatch --------
    _stage("warm_ladder")
    Pw = counts[-1]
    ctx_w = problems(Pw)
    warm_thunks = [
        lambda d=depth: sharded_blocked_qr(ctx_w["A"], ctx_w["cmesh"],
                                           block_size=ctx_w["nb"],
                                           lookahead=True, overlap_depth=d)
        for depth in DEPTHS
    ]

    def warm_pass_rps() -> float:
        t0 = time.perf_counter()
        for _ in range(WARM_DISPATCHES):
            for thunk in warm_thunks:
                jax.block_until_ready(thunk())
        return (WARM_DISPATCHES * len(warm_thunks)) / (
            time.perf_counter() - t0)

    with _Watchdog("warm_ladder", 2400):
        # Settle passes (serving_pulse methodology): measure the warm
        # labels once so the armed arm never captures, drift the
        # post-compile throttle out of both arms.
        store = pulse_mod.arm(max_reports=64)
        warm_pass_rps()
        pulse_mod.disarm()
        warm_pass_rps()
        disarmed, armed = [], []
        captures_before = store.stats()["captures"]
        compiles_before = compiles["n"]
        for rep_i in range(WARM_REPEATS):
            def one_armed() -> float:
                pulse_mod.arm(store=store)
                try:
                    return warm_pass_rps()
                finally:
                    pulse_mod.disarm()
            if rep_i % 2 == 0:
                disarmed.append(warm_pass_rps())
                armed.append(one_armed())
            else:
                armed.append(one_armed())
                disarmed.append(warm_pass_rps())
        recaptures_armed = store.stats()["captures"] - captures_before
        recompiles_armed = compiles["n"] - compiles_before
        overhead_ratio = statistics.median(armed) / statistics.median(
            disarmed)
    emit({"metric": "serving_overlap", "phase": "warm_disarmed",
          "devices": Pw,
          "dispatches_per_s": [round(r, 1) for r in disarmed],
          "median_rps": round(statistics.median(disarmed), 1)})
    emit({"metric": "serving_overlap", "phase": "warm_armed",
          "devices": Pw,
          "dispatches_per_s": [round(r, 1) for r in armed],
          "median_rps": round(statistics.median(armed), 1),
          "armed_over_disarmed": round(overhead_ratio, 4),
          "recaptures_armed": recaptures_armed,
          "recompiles_armed": recompiles_armed})

    # ---- verdict ---------------------------------------------------------
    ok = (order_ok and census_ok and wire_ok and bit_identical
          and gated == cells and warm_recompiles == 0
          and overhead_ratio >= 0.95 and recaptures_armed == 0
          and recompiles_armed == 0)
    emit({
        "metric": "serving_overlap_verdict",
        "kind": "verdict",
        "value": round(overhead_ratio, 4),
        "unit": "armed/disarmed warm pipelined dispatch rate",
        "order_meets_depth": bool(order_ok),
        "census_launches_identical_volume_in_ceiling": bool(census_ok),
        "wire_reduction_meets_bar": bool(wire_ok),
        "pipeline_bit_identical_to_lookahead": bool(bit_identical),
        "residual_cells": cells,
        "residual_cells_within_8x": gated,
        "worst_residual_ratio": round(worst, 4),
        "no_silent_garbage": bool(gated == cells),
        "warm_recompiles_pipelined": warm_recompiles,
        "armed_within_5pct": bool(overhead_ratio >= 0.95),
        "zero_recaptures_armed": recaptures_armed == 0,
        "zero_recompiles_armed": recompiles_armed == 0,
        "depths": list(DEPTHS),
        "topologies": list(counts),
        "ok": bool(ok),
    })
    _stage("done")


if __name__ == "__main__":
    main()
