"""Round-3 session-2 TPU tuning probe (single chip, watchdogged stages).

Questions this answers, each as one JSONL line on stdout:

1. ``geqrf_backward_error_1024`` — does the platform's own
   ``lax.linalg.geqrf`` (quoted as a comparison datum in README/bench)
   meet the < 1e-5 backward-error target our engine is held to? If not,
   its higher GFLOP/s is not an apples-to-apples ceiling.
2. ``qr_4096_nb256_pallas`` under the ambient ``DHQR_MAX_PANELS`` — run
   once with 8 (default) and once with 16 to price the two-level scan's
   masked-flop overhead against program size (ops/blocked.py docstring).
3. ``qr_8192_nb256_pallas`` — nb=256 at m=8192 exceeds the kernel's VMEM
   gate for the tallest super-blocks, so the engine mixes XLA panels
   (early super-blocks) with Pallas panels (later, shorter ones); is the
   mix ahead of the all-Pallas nb=128 9,970 GFLOP/s?
4. ``qr_16384_nb128_pallas`` — the BASELINE.md north-star size on one
   chip (the target itself is v4-32); chain=3 suffices because device
   time (~0.5-1 s) dwarfs the ~60-90 ms tunnel RTT.

Run ONE instance at a time (the axon relay allows a single TPU process).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main() -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    from bench import _Watchdog  # same hard-exit escape for hung PJRT calls

    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(os.path.dirname(os.path.dirname(
                          os.path.abspath(__file__))), ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from dhqr_tpu.ops.blocked import MAX_UNROLLED_PANELS, _blocked_qr_impl
    from dhqr_tpu.utils.profiling import sync

    _stage("backend_init")
    with _Watchdog("backend_init", 150):
        platform = jax.devices()[0].platform
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    rng = np.random.default_rng(0)

    def emit(rec):
        rec["platform"] = platform
        print(json.dumps(rec), flush=True)

    # 1. geqrf accuracy at 1024 (its GFLOP/s datum already exists).
    if os.environ.get("TUNE_GEQRF", "1") == "1":
        _stage("geqrf_accuracy")
        try:
            with _Watchdog("geqrf_accuracy", 120):
                from jax._src.lax.linalg import geqrf, householder_product

                A = jnp.asarray(rng.random((1024, 1024)), jnp.float32)

                @jax.jit
                def backward_err(A):
                    packed, taus = geqrf(A)
                    Q = householder_product(packed, taus)
                    R = jnp.triu(packed)
                    return jnp.linalg.norm(Q @ R - A) / jnp.linalg.norm(A)

                e = float(backward_err(A))
                emit({"metric": "geqrf_backward_error_1024", "value": e,
                      "meets_1e-5": e < 1e-5})
        except Exception as ex:
            print(f"::stage_failed geqrf {type(ex).__name__}: {ex}",
                  file=sys.stderr, flush=True)

    def chain_time(n, nb, chain, watchdog, pallas=True, repeats=3):
        name = f"qr_{n}_nb{nb}" + ("_pallas" if pallas else "")
        _stage(name)
        try:
            with _Watchdog(name, watchdog):
                A = jnp.asarray(rng.random((n, n)), jnp.float32)
                sync(A)
                kw = dict(precision="highest", pallas=pallas, norm="fast",
                          panel_impl="loop")
                t0 = time.perf_counter()
                single = _blocked_qr_impl.lower(A, nb, **kw).compile()
                H, al = single(A)
                sync(al)

                def chained(A):
                    def body(C, _):
                        Hc, ac = _blocked_qr_impl(C, nb, **kw)
                        return Hc, ac[0]
                    return lax.scan(body, A, None, length=chain)

                ck = jax.jit(chained).lower(A).compile()
                compile_s = time.perf_counter() - t0
                Hc, s = ck(A)
                sync(s)

                def tmin(f, out):
                    ts = []
                    for _ in range(repeats):
                        t0 = time.perf_counter()
                        r = f(A)
                        sync(r[1] if out else r[1])
                        ts.append(time.perf_counter() - t0)
                    return min(ts)

                t1 = tmin(single, False)
                tk = tmin(ck, True)
                t = (tk - t1) / (chain - 1)
                unreliable = not (tk > t1 * 1.05 and t > 0)
                if unreliable:
                    t = t1
                flops = (4.0 / 3.0) * n**3
                emit({"metric": f"qr_gflops_per_chip_f32_{n}x{n}",
                      "value": round(flops / t / 1e9, 2), "unit": "GFLOP/s",
                      "seconds": round(t, 4), "block_size": nb,
                      "pallas_panels": pallas, "chain_length": chain,
                      "seconds_single_dispatch": round(t1, 4),
                      "seconds_chain": round(tk, 4),
                      "compile_seconds": round(compile_s, 2),
                      "max_unrolled_panels": MAX_UNROLLED_PANELS,
                      "chain_unreliable": unreliable})
        except Exception as ex:
            print(f"::stage_failed {name} {type(ex).__name__}: {ex}",
                  file=sys.stderr, flush=True)

    stages = os.environ.get("TUNE_STAGES", "4096,8192,16384").split(",")
    if "4096" in stages:
        chain_time(4096, 256, 25, 360)
    if "8192" in stages:
        chain_time(8192, 256, 5, 420)
    if "16384" in stages:
        chain_time(16384, 128, 3, 540, repeats=2)
    _stage("done")


if __name__ == "__main__":
    main()
