"""Round-5 TPU probe: Householder-reconstruction panels vs the fused sweep.

``panel_impl="reconstruct"`` factors panels with the backend's explicit
QR and reconstructs the packed reflectors (GEMM-shaped algebra;
ops/householder._panel_qr_reconstruct); ``"reconstruct:<chunk>"`` routes
the explicit QR through a two-level TSQR tree (batched chunk QRs + one
combine) for backends whose monolithic tall-matrix QR lowering is slow.
Stages measure, per (n, nb): the all-Pallas baseline (the committed
headline config), direct reconstruct, and two tree chunk sizes — all
with pallas=False for the reconstruct rows so the panel_impl actually
routes.

Run ONE instance at a time (the axon relay allows a single TPU process).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main() -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    from bench import _Watchdog

    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from dhqr_tpu.ops.blocked import _apply_q_impl, _blocked_qr_impl
    from dhqr_tpu.ops.solve import r_matrix
    from dhqr_tpu.utils.profiling import sync

    _stage("backend_init")
    with _Watchdog("backend_init", 150):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    rng = np.random.default_rng(0)

    def emit(rec):
        rec["platform"] = platform
        rec["device_kind"] = kind
        print(json.dumps(rec), flush=True)

    def chain_time(n, nb, chain, watchdog, panel, pallas, repeats=3,
                   backward_error=False):
        name = f"qr_{n}_nb{nb}_{panel.replace(':', '-')}" + \
            ("_pallas" if pallas else "")
        _stage(name)
        try:
            with _Watchdog(name, watchdog):
                A = jnp.asarray(rng.random((n, n)), jnp.float32)
                sync(A)
                kw = dict(precision="highest", pallas=pallas, norm="fast",
                          panel_impl=panel)
                t0 = time.perf_counter()
                single = _blocked_qr_impl.lower(A, nb, **kw).compile()
                H, al = single(A)
                sync(al)

                def chained(A):
                    def body(C, _):
                        Hc, ac = _blocked_qr_impl(C, nb, **kw)
                        return Hc, ac[0]
                    return lax.scan(body, A, None, length=chain)

                ck = jax.jit(chained).lower(A).compile()
                compile_s = time.perf_counter() - t0
                Hc, s = ck(A)
                sync(s)

                def tmin(f, pick):
                    ts = []
                    for _ in range(repeats):
                        t0 = time.perf_counter()
                        r = f(A)
                        sync(pick(r))
                        ts.append(time.perf_counter() - t0)
                    return min(ts)

                t1 = tmin(single, lambda r: r[1])
                tk = tmin(ck, lambda r: r[1])
                t = (tk - t1) / (chain - 1)
                unreliable = not (tk > t1 * 1.05 and t > 0)
                if unreliable:
                    t = t1
                flops = (4.0 / 3.0) * n**3
                rec = {"metric": f"qr_gflops_per_chip_f32_{n}x{n}",
                       "value": round(flops / t / 1e9, 2),
                       "unit": "GFLOP/s", "seconds": round(t, 4),
                       "block_size": nb, "panel_impl": panel,
                       "pallas_panels": pallas,
                       "chain_length": chain,
                       "seconds_single_dispatch": round(t1, 4),
                       "seconds_chain": round(tk, 4),
                       "compile_seconds": round(compile_s, 2),
                       "chain_unreliable": unreliable}
                if backward_error:
                    QR = _apply_q_impl(H, r_matrix(H, al), nb,
                                       precision="highest")
                    rec[f"backward_error_{n}"] = float(
                        jnp.linalg.norm(QR - A) / jnp.linalg.norm(A))
                emit(rec)
        except Exception as ex:
            emit({"metric": name, "ok": False,
                  "error": f"{type(ex).__name__}: {ex}"[:400]})

    # Accuracy first (cheap); baseline half of each group is the
    # committed-config control. Smallest-first; tree chunks bracket the
    # VMEM-friendly range.
    chain_time(1024, 256, 5, 240, "reconstruct", False, backward_error=True)
    chain_time(4096, 256, 25, 560, "loop", True)            # baseline
    chain_time(4096, 256, 25, 560, "reconstruct", False)
    chain_time(4096, 256, 25, 560, "reconstruct:1024", False)
    chain_time(4096, 256, 25, 560, "reconstruct:2048", False)
    chain_time(12288, 512, 3, 580, "loop", True, repeats=2)  # baseline
    chain_time(12288, 512, 3, 580, "reconstruct", False, repeats=2)
    chain_time(12288, 512, 3, 580, "reconstruct:2048", False, repeats=2)
    _stage("done")


if __name__ == "__main__":
    main()
