"""Async serving ladder: seeded open-loop load vs the sync tier.

The round-11 tentpole's decision artifact. Two measured phases against
the same SEEDED round-8 Zipf shape mix, because capacity and latency
are different questions answered at different operating points:

* ``async_capacity`` — every request queued up front (untimed, like
  the sync ceiling's pre-collected list), then a timed ``drain()``:
  the scheduler's own throughput ceiling with full coalescing
  opportunity (the apples-to-apples comparison against the sync
  ``batched_lstsq`` ceiling — what the flush machinery COSTS over bare
  batch dispatch);
* ``open_loop`` — Poisson arrivals (exponential inter-arrival gaps) at
  ``rate_frac`` of the sync ceiling: client-observed latency
  (submit -> future done, the shared bounded ``LatencyHistogram``)
  under live load where arrivals do NOT wait for completions, so
  queueing delay is measured rather than hidden by back-to-back calls
  (arXiv 2112.09017 frames TPU linear algebra as exactly this kind of
  serving workload). Reported requests/s is completions during the
  arrival window over the window (trim-the-cooldown; the post-arrival
  drain tail is a fixed cost a long-running service amortizes away) —
  bounded above by the offered rate; the end-to-end quotient is
  emitted alongside.

Baselines: a warm per-request singles loop (the pre-serve answer) and
the sync ``batched_lstsq`` ceiling — both measured INTERLEAVED with the
capacity passes, round-robin in one time window, because this
shared-CPU container's throughput drifts +-30% across minutes and every
verdict ratio must compare code paths, not machine epochs.

Acceptance (ISSUE 6): open-loop requests/s >= 2x the singles loop,
burst capacity >= 0.9x the sync ceiling, open-loop p99 within the
configured SLO, ZERO recompiles in steady state after prewarm (cache
misses flat across both timed phases), zero admission rejects at the
offered rate, and every request's normal-equations residual within the
reference's 8x LAPACK criterion (runtests.jl:62).

Usage:  python benchmarks/serving_async.py [n_requests] [rate_frac]
        (rate_frac: offered rate as a fraction of the measured ASYNC
         capacity; default 0.8 — high load, but sustainable: the SLO
         phase measures latency at an operating point a service would
         actually run, not at the edge of saturation)
Writes: benchmarks/results/serving_async_<platform>.jsonl (append).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# The round-8 shape ladder verbatim (benchmarks/serving_throughput.py):
# rank-weighted ~ 1/(rank+1)^1.1, all n <= 256, half on-lattice, half
# awkward — the async numbers stay comparable to the sync artifact.
SHAPE_LADDER = [
    (64, 16), (100, 36), (128, 48), (192, 64),
    (250, 100), (384, 128), (500, 180), (640, 256),
]
MICRO_BATCH = 32          # serve max_batch, matching the round-8 runs
SLO_MS = 1000.0           # latency budget each request is submitted with
                          # (must clear the heaviest bucket's ~400 ms CPU
                          # dispatch plus a queueing allowance at 0.9+ load)
# Coalescing window: at ~60 req/s per popular bucket a 100 ms window
# gathers only ~6 requests per flush and per-dispatch overhead dominates
# (measured 0.80x of the sync ceiling); 300 ms grows popular buckets to
# 16-32 while staying far enough under the SLO that rare-bucket requests
# (interval wait + a queued dispatch behind other flushes) keep p99
# inside it — 600 ms measurably blew the p99 budget.
FLUSH_INTERVAL_MS = 300.0
WARM_PASSES = 3


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main(n_requests: int = 512, rate_frac: float = 0.92) -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    from bench import SCHEMA_VERSION, ROUND, _Watchdog

    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np

    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(_REPO, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

    import dhqr_tpu
    from dhqr_tpu.serve import AsyncScheduler, batched_lstsq, prewarm
    from dhqr_tpu.serve.cache import ExecutableCache
    from dhqr_tpu.utils.config import SchedulerConfig, ServeConfig
    from dhqr_tpu.utils.profiling import LatencyHistogram, sync
    from dhqr_tpu.utils.testing import (TOLERANCE_FACTOR,
                                        normal_equations_residual,
                                        oracle_residual)

    _stage("backend_init")
    with _Watchdog("backend_init", 240):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    out_path = os.path.join(_REPO, "benchmarks", "results",
                            f"serving_async_{platform}.jsonl")

    def emit(rec):
        rec.update(platform=platform, device_kind=kind, round=ROUND,
                   schema_version=SCHEMA_VERSION)
        line = json.dumps(rec)
        print(line, flush=True)
        with open(out_path, "a") as f:
            f.write(line + "\n")

    # ---- the request stream (fixed seeds: artifact is reproducible) ----
    rng = np.random.default_rng(0)
    ranks = np.arange(len(SHAPE_LADDER))
    weights = 1.0 / (ranks + 1.0) ** 1.1
    weights /= weights.sum()
    picks = rng.choice(len(SHAPE_LADDER), size=n_requests, p=weights)
    shapes = [SHAPE_LADDER[i] for i in picks]
    As = [jnp.asarray(rng.random(s), jnp.float32) for s in shapes]
    bs = [jnp.asarray(rng.random(s[0]), jnp.float32) for s in shapes]
    sync(As[-1])
    scfg = ServeConfig(max_batch=MICRO_BATCH)

    # ---- prewarm the async cache THROUGH THE SYNC TIER -----------------
    # Deadline/interval flushes launch partial micro-batches, so steady
    # state touches every power-of-two batch bucket up to the cap —
    # prewarm mints them all per ladder shape (one spec per pow2 count;
    # the same keys live dispatch hits, by the shared _plan_key).
    _stage("prewarm")
    with _Watchdog("prewarm", 2400):
        acache = ExecutableCache(max_size=64)
        pow2 = [1 << i for i in range((MICRO_BATCH - 1).bit_length() + 1)
                if 1 << i <= MICRO_BATCH]
        keys = prewarm([(c, m, n) for (m, n) in SHAPE_LADDER for c in pow2],
                       serve_config=scfg, cache=acache)
    emit({"metric": "serving_async", "phase": "prewarm",
          "keys": len(keys), "cache": acache.stats()})

    # ---- throughput triple: sync ceiling / async capacity / singles ----
    # The three rates the verdict compares are measured INTERLEAVED,
    # round-robin in the same time window: this shared-CPU container's
    # throughput drifts +-30% across minutes (cgroup burst credits), so
    # two phases measured minutes apart compare machine epochs, not code
    # paths. One round = one timed sync batched_lstsq pass over the full
    # list, one timed async drain pass of the same list, one timed
    # singles pass over a fixed subset — every ratio is within-round.
    #
    # The async capacity pass queues everything first (UNTIMED, exactly
    # like the sync ceiling's pre-collected list; admission cost under
    # live load is measured by the open-loop phase, where it belongs),
    # then times one drain(): group selection, tenant take, pow2
    # chunking, stack/pad, dispatch, scatter, fence — everything the
    # scheduler adds on top of the engine's shared dispatch path, in
    # manual mode (start=False) so it is single-threaded like
    # batched_lstsq.
    sync_cache = ExecutableCache(max_size=64)
    n_singles = min(256, n_requests)
    _stage("throughput_warmup")
    with _Watchdog("throughput_warmup", 1800):
        for m, n in SHAPE_LADDER:  # pay the singles jit compiles up front
            x = dhqr_tpu.lstsq(jnp.zeros((m, n), jnp.float32) +
                               jnp.eye(m, n, dtype=jnp.float32),
                               jnp.ones((m,), jnp.float32))
            sync(x)
        xs_ref = batched_lstsq(As, bs, serve_config=scfg, cache=sync_cache)
        sync(xs_ref)
    misses_before = acache.stats()["misses"]   # steady state starts here
    cap_sched = AsyncScheduler(
        serve_config=scfg,
        sched_config=SchedulerConfig(slo_ms=30e3,
                                     flush_interval_ms=FLUSH_INTERVAL_MS,
                                     queue_depth=4 * n_requests),
        cache=acache, start=False)
    _stage("throughput_rounds")
    sync_s, drain_s, singles_s = 0.0, 0.0, 0.0
    rounds = []
    with _Watchdog("throughput_rounds", 2400):
        for _ in range(WARM_PASSES):
            t0 = time.perf_counter()
            xs = batched_lstsq(As, bs, serve_config=scfg, cache=sync_cache)
            sync(xs)
            dt_sync = time.perf_counter() - t0
            cap_futs = [cap_sched.submit("lstsq", A, b, deadline=30.0)
                        for A, b in zip(As, bs)]
            t0 = time.perf_counter()
            cap_sched.drain()
            dt_drain = time.perf_counter() - t0
            assert all(f.done() for f in cap_futs)
            t0 = time.perf_counter()
            for A, b in zip(As[:n_singles], bs[:n_singles]):
                x = dhqr_tpu.lstsq(A, b)
                sync(x)
            dt_singles = time.perf_counter() - t0
            sync_s += dt_sync
            drain_s += dt_drain
            singles_s += dt_singles
            rounds.append({
                "sync_rps": round(n_requests / dt_sync, 1),
                "capacity_rps": round(n_requests / dt_drain, 1),
                "singles_rps": round(n_singles / dt_singles, 1),
            })
    ceiling_rps = n_requests * WARM_PASSES / sync_s
    capacity_rps = n_requests * WARM_PASSES / drain_s
    singles_rps = n_singles * WARM_PASSES / singles_s
    cap_stats = cap_sched.stats()
    cap_sched.shutdown()
    emit({"metric": "serving_async", "phase": "sync_ceiling",
          "passes": WARM_PASSES, "requests": n_requests,
          "micro_batch": MICRO_BATCH,
          "requests_per_s": round(ceiling_rps, 1),
          "cache": sync_cache.stats()})
    emit({"metric": "serving_async", "phase": "async_capacity",
          "passes": WARM_PASSES, "requests": n_requests,
          "requests_per_s": round(capacity_rps, 1),
          "fraction_of_ceiling": round(capacity_rps / ceiling_rps, 3),
          "flushes": cap_stats["flushes"],
          "dispatches": cap_stats["dispatches"]})
    emit({"metric": "serving_async", "phase": "singles",
          "passes": WARM_PASSES, "requests": n_singles,
          "requests_per_s": round(singles_rps, 1),
          "rounds": rounds})

    # ---- async open-loop run ------------------------------------------
    # Offered rate is a fraction of the async path's own measured
    # capacity — the operating point a service would pick (utilization
    # against what the serving path sustains, not against a ceiling it
    # cannot reach) — so the queueing load, and with it p99, is actually
    # controlled by rate_frac.
    offered_rps = rate_frac * capacity_rps
    inter = np.random.default_rng(1).exponential(
        1.0 / offered_rps, size=n_requests)
    arrivals = np.cumsum(inter)
    client_lat = LatencyHistogram()       # the shared bounded histogram
    done_at = [0.0] * n_requests
    n_done = [0]
    lock = threading.Lock()

    sched = AsyncScheduler(
        serve_config=scfg,
        sched_config=SchedulerConfig(slo_ms=SLO_MS,
                                     flush_interval_ms=FLUSH_INTERVAL_MS,
                                     queue_depth=4096),
        cache=acache)
    futs = [None] * n_requests

    def on_done(i, t_submit):
        def cb(fut):
            now = time.perf_counter()
            client_lat.record(now - t_submit)
            done_at[i] = now
            with lock:
                n_done[0] += 1
        return cb

    _stage("async_stream")
    with _Watchdog("async_stream", 2400):
        t_start = time.perf_counter()
        rejected = 0
        for i in range(n_requests):
            target = t_start + arrivals[i]
            delay = target - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            t_submit = time.perf_counter()
            try:
                fut = sched.submit("lstsq", As[i], bs[i],
                                   deadline=SLO_MS / 1e3,
                                   tenant=f"t{picks[i]}")
            except Exception:
                rejected += 1
                continue
            fut.add_done_callback(on_done(i, t_submit))
            futs[i] = fut
        if rejected:
            # The run is compromised; finish what was accepted, say so.
            print(f"::open_loop rejected={rejected}", file=sys.stderr,
                  flush=True)
        # Wait for every ACCEPTED request (a one-shot event keyed on
        # n_requests would never fire after a reject and stall here).
        target = n_requests - rejected
        wait_until = time.perf_counter() + 600
        while time.perf_counter() < wait_until:
            with lock:
                if n_done[0] >= target:
                    break
            time.sleep(0.01)
        t_end = max(d for d in done_at if d) if any(done_at) else t_start
    sched_stats = sched.stats()
    sched.shutdown()
    recompiles = acache.stats()["misses"] - misses_before
    # End-to-end: first submit -> last completion, drain tail included.
    end_to_end_rps = (n_requests - rejected) / (t_end - t_start)
    # Stream rate: completions DURING the arrival window over the
    # window — the standard trim-the-cooldown open-loop number (the
    # tail after the last arrival is a fixed cost a long-running
    # service amortizes to nothing; on a seconds-long stream it is a
    # 10-20% haircut). Bounded above by the offered rate: completions
    # in the window can never exceed its arrivals.
    t_arr_end = t_start + arrivals[-1]
    in_window = sum(1 for d in done_at if 0.0 < d <= t_arr_end)
    async_rps = in_window / arrivals[-1]
    emit({"metric": "serving_async", "phase": "open_loop",
          "requests": n_requests, "rejected": rejected,
          "offered_rps": round(offered_rps, 1),
          "rate_frac_of_capacity": rate_frac,
          "requests_per_s": round(async_rps, 1),
          "end_to_end_rps": round(end_to_end_rps, 1),
          "recompiles_steady_state": recompiles,
          "slo_ms": SLO_MS,
          "client_latency": client_lat.snapshot(),
          "scheduler": sched_stats})

    # ---- residuals: every async answer against the 8x criterion -------
    _stage("residuals")
    worst = 0.0
    all_ok = True
    for i, fut in enumerate(futs):
        if fut is None:
            continue
        x = np.asarray(fut.result())
        res = normal_equations_residual(As[i], x, bs[i])
        ref = oracle_residual(np.asarray(As[i]), np.asarray(bs[i]))
        ratio = res / (TOLERANCE_FACTOR * ref)
        worst = max(worst, ratio)
        all_ok = all_ok and ratio < 1.0
    emit({"metric": "serving_async_residuals",
          "requests": n_requests - rejected,
          "criterion": "8x_lapack_normal_equations",
          "all_within": all_ok, "worst_fraction_of_bar": round(worst, 4)})

    # ---- verdict -------------------------------------------------------
    # speedup_vs_singles and p99 come from the OPEN-LOOP phase (live
    # load at the operating point); fraction_of_sync_ceiling from the
    # BURST capacity phase (both sides see the whole list, measured
    # interleaved in the same machine epoch).
    p99_ms = client_lat.snapshot()["p99_ms"]
    ok = (async_rps >= 2.0 * singles_rps
          and capacity_rps >= 0.9 * ceiling_rps
          and p99_ms <= SLO_MS
          and recompiles == 0
          and rejected == 0
          and all_ok)
    emit({"metric": "serving_async_verdict",
          "speedup_vs_singles": round(async_rps / singles_rps, 2),
          "fraction_of_sync_ceiling": round(capacity_rps / ceiling_rps, 3),
          "open_loop_rps": round(async_rps, 1),
          "end_to_end_rps": round(end_to_end_rps, 1),
          "capacity_rps": round(capacity_rps, 1),
          "ceiling_rps": round(ceiling_rps, 1),
          "singles_rps": round(singles_rps, 1),
          "p99_ms": p99_ms, "slo_ms": SLO_MS,
          "p99_within_slo": bool(p99_ms <= SLO_MS),
          "zero_recompiles_steady_state": recompiles == 0,
          "zero_rejects": rejected == 0,
          "all_residuals_within_8x": all_ok,
          "deadline_misses": sched_stats["deadline_misses"],
          "flushes": sched_stats["flushes"],
          "ok": bool(ok)})
    _stage("done")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 2048,
         float(sys.argv[2]) if len(sys.argv) > 2 else 0.80)
