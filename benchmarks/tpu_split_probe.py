"""Round-3 TPU probe: split-panel (2x256 Pallas + WY apply) at nb=512.

The phase probe measured the flat 512-wide kernel's serial sweep at ~1/3
of total QR time; panels now factor as two 256-wide kernel calls with one
compact-WY GEMM apply between (ops/blocked._panel_factor_pallas). This
re-measures every nb=512 config with the split — including 8192/4096
where FLAT 512 lost to 256 (panel cost); if split-512 wins there too the
auto-width threshold drops.

Run ONE instance at a time (the axon relay allows a single TPU process).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main() -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    from bench import _Watchdog

    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from dhqr_tpu.ops.blocked import _apply_q_impl, _blocked_qr_impl
    from dhqr_tpu.ops.solve import r_matrix
    from dhqr_tpu.utils.profiling import sync

    _stage("backend_init")
    with _Watchdog("backend_init", 150):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    rng = np.random.default_rng(0)

    def emit(rec):
        rec["platform"] = platform
        rec["device_kind"] = kind
        print(json.dumps(rec), flush=True)

    def chain_time(n, nb, chain, watchdog, repeats=3,
                   backward_error=False, flat=256):
        """``flat`` is passed explicitly per stage (static jit arg), so one
        process can ladder several flat widths without touching the module
        global / env."""
        name = f"qr_split_{n}_nb{nb}_flat{flat}"
        _stage(name)
        try:
            with _Watchdog(name, watchdog):
                A = jnp.asarray(rng.random((n, n)), jnp.float32)
                sync(A)
                kw = dict(precision="highest", pallas=True, norm="fast",
                          panel_impl="loop", pallas_flat=flat)
                t0 = time.perf_counter()
                single = _blocked_qr_impl.lower(A, nb, **kw).compile()
                H, al = single(A)
                sync(al)

                def chained(A):
                    def body(C, _):
                        Hc, ac = _blocked_qr_impl(C, nb, **kw)
                        return Hc, ac[0]
                    return lax.scan(body, A, None, length=chain)

                ck = jax.jit(chained).lower(A).compile()
                compile_s = time.perf_counter() - t0
                Hc, s = ck(A)
                sync(s)

                def tmin(f, pick):
                    ts = []
                    for _ in range(repeats):
                        t0 = time.perf_counter()
                        r = f(A)
                        sync(pick(r))
                        ts.append(time.perf_counter() - t0)
                    return min(ts)

                t1 = tmin(single, lambda r: r[1])
                tk = tmin(ck, lambda r: r[1])
                t = (tk - t1) / (chain - 1)
                unreliable = not (tk > t1 * 1.05 and t > 0)
                if unreliable:
                    t = t1
                flops = (4.0 / 3.0) * n**3
                rec = {"metric": f"qr_gflops_per_chip_f32_{n}x{n}",
                       "value": round(flops / t / 1e9, 2),
                       "unit": "GFLOP/s", "seconds": round(t, 4),
                       "block_size": nb, "panel": "split-pallas",
                       "pallas_flat": flat,
                       "chain_length": chain,
                       "seconds_single_dispatch": round(t1, 4),
                       "seconds_chain": round(tk, 4),
                       "compile_seconds": round(compile_s, 2),
                       "chain_unreliable": unreliable}
                if backward_error:
                    QR = _apply_q_impl(H, r_matrix(H, al), nb,
                                       precision="highest")
                    rec[f"backward_error_{n}"] = float(
                        jnp.linalg.norm(QR - A) / jnp.linalg.norm(A))
                emit(rec)
        except Exception as ex:
            emit({"metric": name, "ok": False,
                  "error": f"{type(ex).__name__}: {ex}"[:400]})

    # Accuracy evidence first (cheap), then the ladder smallest-first —
    # probe v1's 12288 stage hit its watchdog with a cold cache (the split
    # program is larger; compile order matters on the slow remote leg).
    chain_time(1024, 512, 5, 240, backward_error=True)
    chain_time(4096, 512, 25, 560)
    chain_time(8192, 512, 5, 560)
    chain_time(12288, 512, 3, 580, repeats=2)
    chain_time(16384, 512, 3, 580, repeats=2)
    # Finer split (4x128 kernel calls per 512 panel): more WY applies on
    # the MXU, shorter serial sweeps — bracket the optimum from below.
    chain_time(4096, 512, 25, 560, flat=128)
    chain_time(12288, 512, 3, 580, repeats=2, flat=128)
    # Split-256 (2x128): does the crossover logic hold at the nb=256 sizes?
    chain_time(4096, 256, 25, 560, flat=128)
    chain_time(8192, 256, 5, 560, flat=128)
    # WIDER panels, split-factored: nb=1024 halves the number of trailing
    # passes — fewer, larger GEMMs, so less per-pass masking/fusion
    # overhead (DESIGN.md's ceiling arithmetic puts ~0.12 s of the 16384^2
    # wall in that overhead; the trailing update is NOT bandwidth-bound at
    # this size). The price is a longer in-panel sweep; flat=512 keeps the
    # kernel at the widths already validated on this chip.
    chain_time(1024, 1024, 5, 300, backward_error=True, flat=512)
    chain_time(4096, 1024, 25, 560, flat=512)
    chain_time(12288, 1024, 3, 580, repeats=2, flat=512)
    chain_time(16384, 1024, 3, 580, repeats=2, flat=512)
    _stage("done")


if __name__ == "__main__":
    main()
