"""Round-3 TPU probe: tall-skinny engines on hardware (BASELINE configs 2/5).

First hardware datum for the TSQR and CholeskyQR2 engine families at the
BASELINE.md config-2 shape (65536 x 256 f32) and the config-5 shape
(131072 x 512 lstsq), single chip. Device time per factorization is ~2-20 ms
— far below the axon tunnel's 60-90 ms RTT — so every stage is chain-timed
(k dependent iterations in one dispatch, (t_k - t_1)/(k - 1), same protocol
as bench.py).

Chaining trick: CholeskyQR2 feeds its own orthonormal Q as the next
iteration's input (cond(Q) = 1, stays in the engine's window). TSQR returns
only R, so the chain multiplies A by a data-dependent 1.0
(``where(isfinite(R[0,0]), 1, 0)``) that XLA cannot constant-fold away.

GFLOP/s is reported against the STANDARD dense-QR flop model
2mn^2 - (2/3)n^3 ("useful flops" — what a Householder factorization of the
same shape would cost), so numbers are comparable across engines even
though CholeskyQR2's actual executed flops (~4mn^2 + Q materialization)
and TSQR's (leaf QRs + combine) differ. The model is recorded per line.

Run ONE instance at a time (the axon relay allows a single TPU process).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main() -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    from bench import _Watchdog

    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from dhqr_tpu.ops.cholqr import _cholesky_qr2_impl, _cholqr_lstsq_impl
    from dhqr_tpu.ops.tsqr import _tsqr_lstsq_impl, _tsqr_r_impl
    from dhqr_tpu.utils.profiling import sync

    _stage("backend_init")
    with _Watchdog("backend_init", 150):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    rng = np.random.default_rng(0)

    def emit(rec):
        rec["platform"] = platform
        rec["device_kind"] = kind
        print(json.dumps(rec), flush=True)

    def measure(name, make_single, make_chain, chain, flops, watchdog,
                repeats=3, extra=None):
        _stage(name)
        try:
            with _Watchdog(name, watchdog):
                t0 = time.perf_counter()
                f1 = make_single()
                fk = make_chain()
                compile_s = time.perf_counter() - t0

                def tmin(f):
                    s = f()
                    sync(s)
                    ts = []
                    for _ in range(repeats):
                        t0 = time.perf_counter()
                        s = f()
                        sync(s)
                        ts.append(time.perf_counter() - t0)
                    return min(ts)

                t1, tk = tmin(f1), tmin(fk)
                t = (tk - t1) / (chain - 1)
                unreliable = not (tk > t1 * 1.05 and t > 0)
                if unreliable:
                    t = t1
                rec = {"metric": name, "value": round(flops / t / 1e9, 2),
                       "unit": "GFLOP/s",
                       "flop_model": "2mn^2-(2/3)n^3 (dense-QR-equivalent)",
                       "seconds": round(t, 5), "chain_length": chain,
                       "seconds_single_dispatch": round(t1, 4),
                       "seconds_chain": round(tk, 4),
                       "compile_seconds": round(compile_s, 2),
                       "chain_unreliable": unreliable}
                if extra:
                    rec.update(extra)
                emit(rec)
        except Exception as ex:
            emit({"metric": name, "ok": False,
                  "error": f"{type(ex).__name__}: {ex}"[:500]})

    PREC = "highest"

    def qr_flops(m, n):
        return 2.0 * m * n * n - (2.0 / 3.0) * n**3

    # ---- config 2 shape: 65536 x 256 f32, factor-only ----
    m, n = 65536, 256
    A = jnp.asarray(rng.random((m, n)), jnp.float32)
    sync(A)

    def cholqr_single():
        f = jax.jit(lambda A: _cholesky_qr2_impl(A, PREC, False)[1]) \
            .lower(A).compile()
        return lambda: f(A)[0, 0]

    def cholqr_chain(k):
        def chained(A):
            def body(C, _):
                Q, R = _cholesky_qr2_impl(C, PREC, False)
                return Q, R[0, 0]
            _, s = lax.scan(body, A, None, length=k)
            return s[-1]
        f = jax.jit(chained).lower(A).compile()
        return lambda: f(A)

    measure(f"cholqr2_f32_{m}x{n}",
            cholqr_single, lambda: cholqr_chain(50), 50, qr_flops(m, n), 360,
            extra={"engine": "cholqr2", "note": "chain feeds Q back as A"})

    def tsqr_single(nblk):
        f = jax.jit(lambda A: _tsqr_r_impl(A, nblk, 128, PREC)[0, 0]) \
            .lower(A).compile()
        return lambda: f(A)

    def tsqr_chain(nblk, k):
        def chained(A):
            def body(C, _):
                R = _tsqr_r_impl(C, nblk, 128, PREC)
                keep = jnp.where(jnp.isfinite(R[0, 0]), jnp.float32(1.0),
                                 jnp.float32(0.0))
                return C * keep, R[0, 0]
            _, s = lax.scan(body, A, None, length=k)
            return s[-1]
        f = jax.jit(chained).lower(A).compile()
        return lambda: f(A)

    for nblk in (8, 32):
        measure(f"tsqr_r_f32_{m}x{n}_blocks{nblk}",
                lambda nblk=nblk: tsqr_single(nblk),
                lambda nblk=nblk: tsqr_chain(nblk, 25), 25,
                qr_flops(m, n), 420,
                extra={"engine": "tsqr", "n_blocks": nblk})

    # ---- config 5 shape: 131072 x 512 overdetermined lstsq ----
    m2, n2 = 131072, 512
    A2 = jnp.asarray(rng.random((m2, n2)), jnp.float32)
    b2 = jnp.asarray(rng.random((m2,)), jnp.float32)
    sync(A2)
    sync(b2)

    def chol_lstsq_chain(k):
        def chained(A, b):
            def body(bc, _):
                x = _cholqr_lstsq_impl(A, bc, PREC, False)
                # feed x's magnitude back into b: data dependency without
                # shape games (b stays (m,))
                keep = jnp.where(jnp.isfinite(x[0]), jnp.float32(1.0),
                                 jnp.float32(0.0))
                return bc * keep, x[0]
            _, s = lax.scan(body, b, None, length=k)
            return s[-1]
        f = jax.jit(chained).lower(A2, b2).compile()
        return lambda: f(A2, b2)

    def chol_lstsq_single():
        f = jax.jit(lambda A, b: _cholqr_lstsq_impl(A, b, PREC, False)[0]) \
            .lower(A2, b2).compile()
        return lambda: f(A2, b2)

    measure(f"cholqr_lstsq_f32_{m2}x{n2}",
            chol_lstsq_single, lambda: chol_lstsq_chain(25), 25,
            qr_flops(m2, n2) + 2.0 * m2 * n2, 480,
            extra={"engine": "cholqr2", "config": "BASELINE-5 shape"})

    def tsqr_lstsq_chain(k, nblk=16):
        def chained(A, b):
            def body(bc, _):
                x = _tsqr_lstsq_impl(A, bc, nblk, 128, PREC)
                keep = jnp.where(jnp.isfinite(x[0]), jnp.float32(1.0),
                                 jnp.float32(0.0))
                return bc * keep, x[0]
            _, s = lax.scan(body, b, None, length=k)
            return s[-1]
        f = jax.jit(chained).lower(A2, b2).compile()
        return lambda: f(A2, b2)

    def tsqr_lstsq_single(nblk=16):
        f = jax.jit(lambda A, b: _tsqr_lstsq_impl(A, b, nblk, 128, PREC)[0]) \
            .lower(A2, b2).compile()
        return lambda: f(A2, b2)

    measure(f"tsqr_lstsq_f32_{m2}x{n2}",
            tsqr_lstsq_single, lambda: tsqr_lstsq_chain(25), 25,
            qr_flops(m2, n2) + 2.0 * m2 * n2, 480,
            extra={"engine": "tsqr", "n_blocks": 16,
                   "config": "BASELINE-5 shape"})

    # Accuracy datum at config-2 shape: CholeskyQR2 orthogonality + residual.
    _stage("cholqr_accuracy")
    try:
        with _Watchdog("cholqr_accuracy", 240):
            Q, R = _cholesky_qr2_impl(A, PREC, False)
            orth = float(jnp.linalg.norm(
                jnp.matmul(Q.T, Q, precision="highest") - jnp.eye(n)))
            resid = float(jnp.linalg.norm(
                jnp.matmul(Q, R, precision="highest") - A) /
                jnp.linalg.norm(A))
            emit({"metric": f"cholqr2_accuracy_{m}x{n}",
                  "orthogonality_error": orth, "backward_error": resid,
                  "meets_1e-5": resid < 1e-5})
    except Exception as ex:
        emit({"metric": "cholqr_accuracy", "ok": False,
              "error": f"{type(ex).__name__}: {ex}"[:500]})
    _stage("done")


if __name__ == "__main__":
    main()
