"""Staged TPU bring-up + perf probe (run directly on the pinned axon platform).

Runs an escalating sequence of stages — device query, tiny matmul, growing
QR sizes, Pallas panel validation, precision comparison — logging a
timestamped line before and after each stage to stderr AND to the file
named by ``DHQR_PROBE_LOG`` (default /tmp/tpu_probe.log), so a hang is
attributable to an exact stage even if the process is later killed.

Safety on the fragile axon relay (see VERDICT r1):

* every stage runs under a watchdog thread; on expiry the probe logs the
  stage and exits immediately (``os._exit``) rather than being externally
  SIGKILLed later with no diagnostics;
* the persistent compilation cache is enabled, so a stage that succeeded
  once never recompiles on a re-run;
* stages are ordered smallest-first, and each stage's success is logged
  before the next begins — re-runs can skip completed work with --from.

Usage: python benchmarks/tpu_probe.py [--from STAGE] [--to STAGE]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

LOG = os.environ.get("DHQR_PROBE_LOG", "/tmp/tpu_probe.log")


def log(msg: str) -> None:
    line = f"[{time.strftime('%H:%M:%S')}] {msg}"
    print(line, file=sys.stderr, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


class Watchdog:
    """os._exit(4) if the stage outlives its deadline (a hung PJRT call
    cannot be interrupted by signals — the GIL-released C call never
    returns to the eval loop, so a thread + hard exit is the only out)."""

    def __init__(self, stage: str, seconds: float):
        self.stage, self.seconds = stage, seconds
        self._done = threading.Event()

    def _fire(self):
        if not self._done.wait(self.seconds):
            log(f"WATCHDOG: stage '{self.stage}' exceeded {self.seconds}s — exiting")
            os._exit(4)

    def __enter__(self):
        self._t = threading.Thread(target=self._fire, daemon=True)
        self._t.start()
        return self

    def __exit__(self, *exc):
        self._done.set()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--from", dest="from_stage", default=None)
    parser.add_argument("--to", dest="to_stage", default=None)
    parser.add_argument("--repeats", type=int, default=3)
    args = parser.parse_args()

    stages = []

    def stage(name, seconds=420):
        def deco(fn):
            stages.append((name, seconds, fn))
            return fn
        return deco

    log(f"probe start pid={os.getpid()}")

    with Watchdog("import_jax", 180):
        import jax
        import jax.numpy as jnp
        import numpy as np
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(_REPO, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    log("import ok")

    from dhqr_tpu.ops.blocked import _apply_q_impl, _blocked_qr_impl
    from dhqr_tpu.ops.solve import r_matrix
    from dhqr_tpu.utils.profiling import sync

    rng = np.random.default_rng(0)

    @stage("devices", 240)
    def _devices():
        d = jax.devices()[0]
        return {"platform": d.platform, "device": str(d)}

    @stage("tiny_matmul", 420)
    def _tiny():
        x = jnp.ones((128, 128), dtype=jnp.float32)
        y = x @ x
        return {"ok": float(y[0, 0])}

    def qr_stage(N, nb, precision="highest", pallas=False, norm="accurate"):
        A = jnp.asarray(rng.random((N, N)), dtype=jnp.float32)
        sync(A)
        t0 = time.perf_counter()
        c = _blocked_qr_impl.lower(
            A, nb, precision=precision, pallas=pallas, norm=norm
        ).compile()
        tc = time.perf_counter() - t0
        H, al = c(A)
        sync(al)
        times = []
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            H, al = c(A)
            sync(al)
            times.append(time.perf_counter() - t0)
        t = min(times)
        fl = 2.0 * N * N * N - (2.0 / 3.0) * N ** 3
        rec = {"N": N, "nb": nb, "precision": precision, "pallas": pallas,
               "norm": norm, "compile_s": round(tc, 1), "run_s": round(t, 4),
               "gflops": round(fl / t / 1e9, 1)}
        if N <= 2048:  # backward error: QR - A via explicit Q application
            R = r_matrix(H, al)
            Rp = jnp.zeros_like(A).at[: R.shape[0]].set(R)
            QR = _apply_q_impl(H, Rp, nb, precision=precision)
            rec["backward_error"] = float(
                jnp.linalg.norm(QR - A) / jnp.linalg.norm(A))
        return rec

    @stage("qr_256", 480)
    def _qr256():
        return qr_stage(256, 64)

    @stage("qr_1024", 480)
    def _qr1024():
        return qr_stage(1024, 128)

    @stage("qr_1024_pallas", 480)
    def _qr1024p():
        return qr_stage(1024, 128, pallas=True)

    @stage("qr_4096", 560)
    def _qr4096():
        return qr_stage(4096, 128)

    @stage("qr_4096_pallas", 560)
    def _qr4096p():
        return qr_stage(4096, 128, pallas=True)

    @stage("qr_1024_high", 480)
    def _qr1024h():
        # 3-pass bf16 (Precision.HIGH) vs 6-pass HIGHEST: 2x MXU throughput
        # if the backward error holds under 1e-5. NB "float32" is a JAX
        # alias for HIGHEST, not HIGH — use "high".
        return qr_stage(1024, 128, precision="high")

    @stage("qr_4096_high", 560)
    def _qr4096h():
        return qr_stage(4096, 128, precision="high")

    @stage("qr_8192", 580)
    def _qr8192():
        return qr_stage(8192, 128)

    @stage("qr_4096_fastnorm", 580)
    def _qr4096fn():
        # norm is an explicit engine parameter (distinct jit cache entry),
        # so the comparison runs in-process — no second TPU claim.
        return qr_stage(4096, 128, norm="fast")

    def lstsq_stage(engine, m_, n_):
        # BASELINE config-2 shape on one chip: engine fast-path comparison.
        import dhqr_tpu

        A = jnp.asarray(rng.random((m_, n_)), dtype=jnp.float32)
        b = jnp.asarray(rng.random(m_), dtype=jnp.float32)
        sync(b)
        x = dhqr_tpu.lstsq(A, b, engine=engine)
        sync(x)
        times = []
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            x = dhqr_tpu.lstsq(A, b, engine=engine)
            sync(x)
            times.append(time.perf_counter() - t0)
        t = min(times)
        fl = 2.0 * m_ * n_ * n_ - (2.0 / 3.0) * n_ ** 3 + 4.0 * m_ * n_
        res = float(jnp.linalg.norm(A.T @ (A @ x - b)))
        return {"engine": engine, "shape": f"{m_}x{n_}",
                "run_s": round(t, 4), "gflops": round(fl / t / 1e9, 1),
                "normal_eq_residual": res}

    @stage("tall_skinny_tsqr", 560)
    def _ts_tsqr():
        return lstsq_stage("tsqr", 65536, 256)

    @stage("tall_skinny_cholqr2", 560)
    def _ts_cholqr():
        return lstsq_stage("cholqr2", 65536, 256)

    names = [n for n, _, _ in stages]
    lo = names.index(args.from_stage) if args.from_stage else 0
    hi = names.index(args.to_stage) + 1 if args.to_stage else len(stages)
    for name, seconds, fn in stages[lo:hi]:
        log(f"stage {name} start")
        with Watchdog(name, seconds):
            try:
                rec = fn()
            except Exception as e:  # log and continue to next stage
                log(f"stage {name} FAILED: {type(e).__name__}: {e}")
                continue
        log(f"stage {name} ok {json.dumps(rec)}")
    log("probe done")


if __name__ == "__main__":
    main()
