"""dhqr-xray acceptance: per-executable cost/memory table + armed overhead.

The round-15 tentpole's decision artifact, mirroring the round-14
serving_obs methodology (same shape-ladder prewarm, manual-mode warm
drains, alternating interleaved A/B median-of-5 after settle passes):

* ``prewarm`` — every bucket key compiled through the serve cache's
  one compile entry with capture ARMED: the emitted row carries the
  aggregate analytic/measured flop+byte accounting, and one
  ``xray_table`` row per cache key carries that executable's full
  :class:`XrayReport` (the table ``python -m dhqr_tpu.obs xray``
  renders from this artifact);
* ``warm_disarmed`` / ``warm_armed`` — warm closed-loop serving
  throughput with xray capture disarmed vs ARMED, interleaved.
  Acceptance: armed costs <= 5% requests/s (median ratio >= 0.95) and
  the armed passes compile — and therefore capture — NOTHING (armed
  capture lives on the compile path only; 0 recompiles pinned);
* every emitted record carries the ``xray`` field block
  (``analytic_flops``, ``measured_cost_analysis`` or null-with-reason,
  ``mfu``, ``roofline_bound``) — on this CPU artifact ``mfu`` and
  ``roofline_bound`` are null WITH reasons (no published CPU peak),
  which is exactly the degradation contract; a TPU replay of this same
  script fills them in from the utils/platform table.

Usage:  python benchmarks/serving_xray.py [n_requests]
Writes: benchmarks/results/serving_xray_<platform>.jsonl (append).
"""

from __future__ import annotations

import json
import os
import signal
import statistics
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# A compact slice of the round-8/11/12/14 ladder: enough shape spread
# for a real per-key table without serving_obs's 36-key prewarm bill.
SHAPE_LADDER = [(64, 16), (128, 48), (250, 100), (384, 128)]
MICRO_BATCH = 16
FLUSH_INTERVAL_MS = 100.0
WARM_REPEATS = 5          # median-of per arm (serving_obs methodology)


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main(n_requests: int = 256) -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    from bench import ROUND, SCHEMA_VERSION, _Watchdog

    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np

    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(_REPO, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

    from dhqr_tpu.obs import flops as oflops
    from dhqr_tpu.obs import xray
    from dhqr_tpu.serve import AsyncScheduler, prewarm
    from dhqr_tpu.serve.cache import ExecutableCache
    from dhqr_tpu.utils.config import SchedulerConfig, ServeConfig
    from dhqr_tpu.utils.platform import (device_hbm_gbps,
                                         device_peak_tflops, mfu_fields)
    from dhqr_tpu.utils.profiling import sync

    _stage("backend_init")
    with _Watchdog("backend_init", 240):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    out_path = os.path.join(_REPO, "benchmarks", "results",
                            f"serving_xray_{platform}.jsonl")
    peak = device_peak_tflops(kind)
    bw = device_hbm_gbps(kind)

    def no_peak_reason() -> str:
        return f"no published peak/bandwidth for device_kind {kind!r}"

    def phase_xray(analytic: "float | None",
                   measured: "dict | None" = None,
                   measured_reason: "str | None" = None,
                   seconds: "float | None" = None) -> dict:
        """The xray field block EVERY record of this artifact carries
        (per-phase aggregate; the per-executable truth is in the
        xray_table rows)."""
        blk = {"analytic_flops": analytic}
        if measured is not None:
            blk["measured_cost_analysis"] = measured
        else:
            blk["measured_cost_analysis"] = None
            blk["measured_unavailable"] = (
                measured_reason or "aggregate phase row — per-executable "
                "analysis lives in the xray_table rows")
        if seconds and analytic:
            gflops = analytic / seconds / 1e9
            blk["achieved_gflops"] = round(gflops, 2)
            # The ONE mfu implementation (utils/platform.mfu_fields):
            # this aggregate block, the bench rows and the xray table
            # must never disagree about the basis.
            blk["mfu"] = mfu_fields(gflops, kind).get("mfu")
            if blk["mfu"] is None:
                blk["mfu_reason"] = no_peak_reason()
        else:
            blk["mfu"] = None
            blk["mfu_reason"] = ("no wall time at this phase"
                                 if not seconds else no_peak_reason())
        if peak and bw and measured and measured.get("bytes_accessed") \
                and analytic:
            intensity = analytic / measured["bytes_accessed"]
            ridge = (peak * 1e12) / (bw * 1e9)
            blk["roofline_bound"] = ("compute" if intensity >= ridge
                                     else "memory")
        else:
            blk["roofline_bound"] = None
            blk["roofline_reason"] = no_peak_reason() if not (peak and bw) \
                else "no aggregate byte count at this phase"
        return blk

    def emit(rec):
        rec.update(platform=platform, device_kind=kind, round=ROUND,
                   schema_version=SCHEMA_VERSION)
        line = json.dumps(rec)
        print(line, flush=True)
        with open(out_path, "a") as f:
            f.write(line + "\n")

    # ---- the request stream (fixed seeds: artifact is reproducible) ----
    rng = np.random.default_rng(0)
    ranks = np.arange(len(SHAPE_LADDER))
    weights = 1.0 / (ranks + 1.0) ** 1.1
    weights /= weights.sum()
    picks = rng.choice(len(SHAPE_LADDER), size=n_requests, p=weights)
    shapes = [SHAPE_LADDER[i] for i in picks]
    As = [jnp.asarray(rng.random(s), jnp.float32) for s in shapes]
    bs = [jnp.asarray(rng.random(s[0]), jnp.float32) for s in shapes]
    sync(As[-1])
    scfg = ServeConfig(max_batch=MICRO_BATCH)
    # Useful work of ONE full stream pass (the closed-form model; the
    # serve tier pads to buckets, so measured exceeds this — which is
    # the point of carrying both).
    stream_flops = float(sum(oflops.lstsq_flops(m, n) for m, n in shapes))

    # ---- prewarm with capture armed: the per-key table ------------------
    _stage("prewarm_xray")
    with _Watchdog("prewarm_xray", 2400):
        acache = ExecutableCache(max_size=64)
        pow2 = [1 << i for i in range((MICRO_BATCH - 1).bit_length() + 1)
                if 1 << i <= MICRO_BATCH]
        with xray.captured(max_reports=256) as store:
            keys = prewarm(
                [(c, m, n) for (m, n) in SHAPE_LADDER for c in pow2],
                serve_config=scfg, cache=acache)
            reports = store.reports()
            store_stats = store.stats()
    agg_flops = sum(r.measured.get("flops", 0.0)
                    for r in reports if r.measured)
    agg_bytes = sum(r.measured.get("bytes accessed", 0.0)
                    for r in reports if r.measured)
    agg_analytic = sum(r.analytic_flops or 0.0 for r in reports)
    measured_agg = ({"flops": agg_flops, "bytes_accessed": agg_bytes}
                    if agg_flops else None)
    emit({"metric": "serving_xray", "phase": "prewarm",
          "keys": len(keys), "captured": store_stats["captures"],
          "unsupported": store_stats["unsupported"],
          "cache": acache.stats(),
          "xray": phase_xray(agg_analytic, measured=measured_agg)})
    for rep in reports:
        row = rep.to_json()
        row["mfu"] = None
        row["mfu_reason"] = ("compile-time capture has no execution "
                             "wall time; pair with dispatch timing "
                             "or the bench stages for MFU")
        emit({"metric": "serving_xray", "phase": "xray_table",
              "xray": row})

    # ---- warm closed-loop throughput, disarmed vs armed ----------------
    def warm_drain_rps() -> float:
        """Manual-mode closed loop (serving_obs methodology verbatim:
        the phase measures the INSTRUMENTATION delta; threaded drains
        carry +-30% scheduling jitter that would drown a few None
        checks)."""
        sched = AsyncScheduler(
            serve_config=scfg,
            sched_config=SchedulerConfig(slo_ms=60e3, queue_depth=16384,
                                         flush_interval_ms=FLUSH_INTERVAL_MS),
            cache=acache, start=False)
        drain_s = 0.0
        for _ in range(2):
            futs = [sched.submit("lstsq", A, b, deadline=60.0)
                    for A, b in zip(As, bs)]
            t0 = time.perf_counter()
            sched.drain()
            drain_s += time.perf_counter() - t0
            assert all(f.exception() is None for f in futs)
        sched.shutdown()
        return 2 * n_requests / drain_s

    _stage("warm_ladder")
    with _Watchdog("warm_ladder", 2400):
        warm_drain_rps()                      # settle passes: keep the
        warm_drain_rps()                      # post-prewarm throttle
        # drift out of both arms (serving_obs measured the first
        # post-compile samples reading low on this shared CPU).
        disarmed, armed = [], []
        misses_before_armed = acache.stats()["misses"]
        captures_armed = 0
        for rep in range(WARM_REPEATS):
            def one_armed() -> float:
                nonlocal captures_armed
                with xray.captured(max_reports=256) as wstore:
                    rps = warm_drain_rps()
                    captures_armed += wstore.stats()["captures"]
                return rps
            if rep % 2 == 0:
                disarmed.append(warm_drain_rps())
                armed.append(one_armed())
            else:
                armed.append(one_armed())
                disarmed.append(warm_drain_rps())
        armed_recompiles = acache.stats()["misses"] - misses_before_armed
        overhead_ratio = statistics.median(armed) / statistics.median(
            disarmed)
    med_dis = statistics.median(disarmed)
    med_arm = statistics.median(armed)
    emit({"metric": "serving_xray", "phase": "warm_disarmed",
          "requests_per_s": [round(r, 1) for r in disarmed],
          "median_rps": round(med_dis, 1),
          "xray": phase_xray(stream_flops * 2,
                             seconds=2 * n_requests / med_dis)})
    emit({"metric": "serving_xray", "phase": "warm_armed",
          "requests_per_s": [round(r, 1) for r in armed],
          "median_rps": round(med_arm, 1),
          "armed_over_disarmed": round(overhead_ratio, 4),
          "recompiles_armed": armed_recompiles,
          "captures_armed": captures_armed,
          "xray": phase_xray(stream_flops * 2,
                             seconds=2 * n_requests / med_arm)})

    # ---- verdict -------------------------------------------------------
    table_ok = bool(reports) and all(
        (r.analytic_flops or 0) > 0
        and (r.measured is not None or r.measured_unavailable)
        for r in reports)
    ok = (overhead_ratio >= 0.95 and armed_recompiles == 0
          and captures_armed == 0 and table_ok
          and store_stats["captures"] == len(keys))
    emit({"metric": "serving_xray_verdict",
          "armed_over_disarmed": round(overhead_ratio, 4),
          "armed_within_5pct": overhead_ratio >= 0.95,
          "zero_recompiles_armed": armed_recompiles == 0,
          "zero_captures_warm": captures_armed == 0,
          "every_key_captured": store_stats["captures"] == len(keys),
          "every_report_complete": table_ok,
          "keys": len(keys),
          "ok": bool(ok),
          "xray": phase_xray(agg_analytic, measured=measured_agg)})
    _stage("done")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 256)
