"""Plan-autotuner A/B grid: static defaults vs. tuned plans, any backend.

The round-9 decision artifact (benchmarks/README "Round-9 decision
rules"): for every shape in a grid spanning the regimes the engine
family was built for — square, tall-skinny (m/n >= 32), and small-n —
run the full ``dhqr_tpu.tune`` search and emit one JSONL row with the
static-default time, the tuned-plan time, the measured speedup, the
winning plan, and the verified residual ratio (every timed candidate
already had to pass the 8x LAPACK normal-equations criterion inside the
search, so a row in this file IS an accuracy-qualified measurement).

After the grid, two warm-path proofs:

* a repeat pass through the PUBLIC ``lstsq(plan="auto")`` for every
  grid shape, pinned to zero recompiles (the DB resolves to programs
  the tune already compiled);
* a serve prewarm (``plan="auto"``) + live ``batched_lstsq`` dispatch +
  repeat, pinned to zero cache misses after prewarm.

Ends with a ``plan_autotune_verdict`` row: geometric-mean speedup over
the grid (the >= 1.3x acceptance bar), whether at least one tall-skinny
shape routed off the householder family, and the zero-recompile flags.

Usage:  python benchmarks/plan_autotune.py
Writes: benchmarks/results/plan_autotune_<platform>.jsonl (append)
        and the tuned plan DB at DHQR_TUNE_DB (or its default path).
"""

from __future__ import annotations

import json
import math
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# Grid: (label, m, n). Small-n and tall-skinny rows are where shape-
# sensitivity lives; the square rows keep the tuner honest at the sizes
# the static defaults were chosen for.
SHAPES = [
    ("square", 512, 512),
    ("square", 1024, 1024),
    ("mid", 1024, 256),
    ("small_n", 256, 16),
    ("small_n", 512, 32),
    ("tall_skinny", 2048, 64),
    ("tall_skinny", 4096, 64),
    ("tall_skinny", 8192, 128),
]


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main() -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    rnd = int(os.environ.get("DHQR_ROUND", "9"))
    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np

    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(_REPO, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

    from bench import SCHEMA_VERSION, _Watchdog

    from dhqr_tpu.models.qr_model import _lstsq_impl, lstsq
    from dhqr_tpu.ops.cholqr import _cholqr_lstsq_impl
    from dhqr_tpu.ops.tsqr import _tsqr_lstsq_impl
    from dhqr_tpu.tune import default_db, tune
    from dhqr_tpu.tune.search import _problem
    from dhqr_tpu.utils.profiling import sync

    _stage("backend_init")
    with _Watchdog("backend_init", 240):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    on_tpu = platform == "tpu"
    out_path = os.path.join(_REPO, "benchmarks", "results",
                            f"plan_autotune_{platform}.jsonl")
    db = default_db()

    def emit(rec):
        rec.update(platform=platform, device_kind=kind, round=rnd,
                   schema_version=SCHEMA_VERSION)
        line = json.dumps(rec)
        print(line, flush=True)
        with open(out_path, "a") as f:
            f.write(line + "\n")

    def _compiles():
        return sum(f._cache_size() for f in
                   (_lstsq_impl, _cholqr_lstsq_impl, _tsqr_lstsq_impl))

    speedups = []
    routed_off_householder = False
    rows = []
    for label, m, n in SHAPES:
        name = f"tune_lstsq_{m}x{n}"
        _stage(name)
        with _Watchdog(name, 560 if on_tpu else 300):
            res = tune("lstsq", m, n, repeats=3, db=db)
        winner = next(r for r in res.measurements if r.plan == res.plan)
        disq = [(r.plan.describe(), r.reason or "accuracy")
                for r in res.measurements if r.seconds is None]
        if res.plan.engine != "householder" and label == "tall_skinny":
            routed_off_householder = True
        speedups.append(res.speedup)
        row = {
            "metric": f"plan_autotune_lstsq_{m}x{n}",
            "regime": label,
            "value": round(res.speedup, 4), "unit": "x vs static default",
            "seconds": round(res.seconds, 6),
            "baseline_seconds": round(res.baseline_seconds, 6),
            "plan": res.plan.to_dict(),
            "plan_desc": res.plan.describe(),
            "residual_ratio_vs_lapack": winner.residual,
            "residual_criterion": 8.0,
            "candidates_timed": sum(
                1 for r in res.measurements if r.seconds is not None),
            "candidates_disqualified": disq,
            "db_key": res.key,
        }
        rows.append(row)
        emit(row)

    # Warm repeat through the PUBLIC tuned path: every shape, twice,
    # zero recompiles (the DB must resolve to already-compiled programs).
    _stage("warm_repeat")
    n_compiled = _compiles()
    for _, m, n in SHAPES:
        A, b = _problem("lstsq", m, n, "float32", seed=0)
        for _ in range(2):
            sync(lstsq(A, b, plan="auto"))
    warm_recompiles = _compiles() - n_compiled

    # Tuned serving: prewarm resolves + compiles per bucket, live
    # dispatch and its repeat must be pure cache hits.
    _stage("serve_warm")
    from dhqr_tpu.serve import batched_lstsq, prewarm
    from dhqr_tpu.serve.cache import ExecutableCache

    rng = np.random.default_rng(0)
    cache = ExecutableCache(max_size=32)
    keys = prewarm([(4, 384, 128), (8, 96, 24)], kind="lstsq",
                   plan="auto", cache=cache)
    misses_after_prewarm = cache.stats()["misses"]
    reqs = [(384, 128)] * 4 + [(96, 24)] * 8
    As = [jnp.asarray(rng.random(s), jnp.float32) for s in reqs]
    bs = [jnp.asarray(rng.random(s[0]), jnp.float32) for s in reqs]
    for _ in range(2):
        xs = batched_lstsq(As, bs, plan="auto", cache=cache)
    sync(xs)
    serve_recompiles = cache.stats()["misses"] - misses_after_prewarm

    geomean = math.exp(sum(math.log(s) for s in speedups) / len(speedups))
    emit({
        "metric": "plan_autotune_verdict",
        "value": round(geomean, 4), "unit": "geomean x vs static default",
        "shapes": len(SHAPES),
        "per_shape_speedups": {f"{m}x{n}": round(s, 3) for (_, m, n), s
                               in zip(SHAPES, speedups)},
        "geomean_meets_1p3x": geomean >= 1.3,
        "tall_skinny_routed_to_alt_engine": routed_off_householder,
        "warm_repeat_recompiles": warm_recompiles,
        "serve_prewarmed_keys": len(keys),
        "serve_dispatch_recompiles": serve_recompiles,
        "all_rows_within_8x_lapack": all(
            (r["residual_ratio_vs_lapack"] or 0) <= 8.0 for r in rows),
        "plan_db": db.path,
    })
    _stage("done")


if __name__ == "__main__":
    main()
