"""Reference-endpoint sweep artifact: 4400x4000, Float64 + ComplexF128.

The reference's integration sweep tops out at m x n = 4400 x 4000 (m = 1.1 n)
over {Float64, ComplexF64} (reference test/runtests.jl:42-43), checked with
the 8x normal-equations criterion (runtests.jl:62,81) and timed against
LAPACK (runtests.jl:84-89). This script reproduces that endpoint on the
distributed tier — 8-device mesh (virtual CPU mesh off-TPU, the reference's
local fake cluster) — asserts the same criterion, and writes the result to
``benchmarks/results/sweep_4400x4000.json`` so the numbers are an artifact,
not prose (VERDICT r2 next-round #6). ``pytest -m slow
tests/test_reference_endpoint.py`` runs the same sweep through pytest.

Usage:  python benchmarks/sweep_reference_endpoint.py [--devices 8] [--full]

``--full`` runs the reference's ENTIRE ladder — (110,100) doubling to
(4400,4000), every size x {Float64, ComplexF64} (runtests.jl:42-43), 14
cells — and writes ``sweep_reference_ladder.json`` (VERDICT r3 missing #2:
only the endpoint pair was committed before round 4).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The reference's exact integration ladder (test/runtests.jl:42): m = 1.1 n,
# n doubling 100 -> 4000 (with the 1000 step), tall throughout.
REFERENCE_LADDER = (
    (110, 100), (220, 200), (440, 400), (880, 800),
    (1100, 1000), (2200, 2000), (4400, 4000),
)


def run_sweep(n_devices: int = 8, sizes=((4400, 4000),),
              dtypes=("float64", "complex128")) -> dict:
    """Run the endpoint sweep; returns the artifact dict (asserts 8x)."""
    sys.path.insert(0, _REPO)
    import jax

    from dhqr_tpu.utils.platform import (
        cpu_requested,
        enable_compile_cache,
        force_cpu_platform,
    )

    if cpu_requested():
        force_cpu_platform()
    enable_compile_cache()
    if jax.default_backend() != "tpu":
        jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
    import numpy as np

    import dhqr_tpu
    from dhqr_tpu.parallel.mesh import column_mesh
    from dhqr_tpu.utils.profiling import sync
    from dhqr_tpu.utils.testing import (
        TOLERANCE_FACTOR,
        lapack_lstsq,
        normal_equations_residual,
        oracle_residual,
        random_problem,
    )

    ndev = min(n_devices, len(jax.devices()))
    mesh = column_mesh(ndev)
    artifact = {
        "sweep": "reference endpoint (test/runtests.jl:42-43)",
        "platform": jax.default_backend(),
        "mesh_devices": ndev,
        "criterion": "normal-equations residual < 8x LAPACK (runtests.jl:62,81)",
        "cases": [],
    }
    for m, n in sizes:
        for dtype_name in dtypes:
            dtype = np.dtype(dtype_name)
            A, b = random_problem(m, n, dtype, seed=0)
            Aj, bj = jnp.asarray(A), jnp.asarray(b)
            # warm = compile; the reference has no compile stage to time
            t0 = time.perf_counter()
            x = dhqr_tpu.lstsq(Aj, bj, mesh=mesh)
            sync(x)
            t_cold = time.perf_counter() - t0
            t0 = time.perf_counter()
            x = dhqr_tpu.lstsq(Aj, bj, mesh=mesh)
            sync(x)
            t_warm = time.perf_counter() - t0
            t0 = time.perf_counter()
            lapack_lstsq(A, b)
            t_lapack = time.perf_counter() - t0
            res = normal_equations_residual(A, np.asarray(x), b)
            ref = oracle_residual(A, b)
            ok = bool(res < TOLERANCE_FACTOR * ref)
            case = {
                "m": m, "n": n, "dtype": dtype_name,
                "residual": res, "lapack_residual": ref,
                "tolerance": TOLERANCE_FACTOR * ref, "pass": ok,
                "seconds_warm": round(t_warm, 3),
                "seconds_cold_incl_compile": round(t_cold, 3),
                "lapack_seconds": round(t_lapack, 3),
                "slowdown_vs_lapack_warm": round(t_warm / max(t_lapack, 1e-9), 2),
            }
            artifact["cases"].append(case)
            print(json.dumps(case), flush=True)
            assert ok, f"8x criterion FAILED for {m}x{n} {dtype_name}"
    return artifact


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--devices", type=int, default=8)
    parser.add_argument("--full", action="store_true",
                        help="the whole reference ladder, not just the "
                             "4400x4000 endpoint")
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)
    if args.out is None:
        args.out = os.path.join(
            _REPO, "benchmarks", "results",
            "sweep_reference_ladder.json" if args.full
            else "sweep_4400x4000.json")

    # Hardware needs explicit opt-in (DHQR_SWEEP_TPU=1 or JAX_PLATFORMS
    # naming tpu); ambient axon + a wedged relay would hang the first
    # backend touch (shared recipe in _axon_env, round-4 hardening).
    sys.path.insert(0, _REPO)
    from _axon_env import default_to_virtual_cpu

    default_to_virtual_cpu(args.devices, optin_env="DHQR_SWEEP_TPU")

    artifact = run_sweep(
        args.devices,
        sizes=REFERENCE_LADDER if args.full else ((4400, 4000),))
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=2)
    print(f"# artifact written to {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
