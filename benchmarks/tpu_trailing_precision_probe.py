"""Round-4 TPU probe: split trailing-update precision (VERDICT r3 #3).

``trailing_precision`` lets the trailing-update GEMMs — ~all the flops —
run at MXU precision "high" (3 bf16 passes) while the panel factorization
and T-factor recurrence stay at "highest" (6 passes). Halving MXU passes
on the bulk work could be the largest single perf lever available; this
probe measures BOTH sides of the trade at 4096/8192/16384:

* backward error ||QR - A|| / ||A|| vs the 1e-5 BASELINE.md target (the
  bound must hold with >= 5x margin before the pair becomes the bench
  configuration, per the VERDICT's own bar);
* chain-timed GFLOP/s (the RTT-cancelling protocol from bench.py).

Emits one JSONL row per (size, precision-pair). Run ONE instance at a
time (single TPU process rule); smallest-first with 560-580 s watchdogs
(compile-heavy stages must not hard-exit mid-remote-compile — the round-3
wedge).

Prior evidence (tpu_r3_vmem_probe.jsonl): one unpaired tp="high" run at
4096^2/nb=256 measured 9,777 GFLOP/s with backward error 2.7e-5 — SLOWER
than the committed tp=None nb=256 rate (10.3 TF/s, different run) and
ABOVE the 1e-5 target. This probe exists to settle it with back-to-back
pairs per size; expect a documented negative result unless the pairing
flips the speed story (run-to-run spread on the shared chip is +-15%).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main() -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    from bench import _Watchdog

    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from dhqr_tpu.ops.blocked import _apply_q_impl, _blocked_qr_impl
    from dhqr_tpu.ops.solve import r_matrix
    from dhqr_tpu.utils.profiling import sync

    _stage("backend_init")
    with _Watchdog("backend_init", 240):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    rng = np.random.default_rng(0)

    def emit(rec):
        rec["platform"] = platform
        rec["device_kind"] = kind
        print(json.dumps(rec), flush=True)

    def stage(n, nb, tprec, chain, watchdog, repeats=3):
        name = f"qr_{n}_nb{nb}_tp-{tprec or 'none'}"
        _stage(name)
        try:
            with _Watchdog(name, watchdog):
                A = jnp.asarray(rng.random((n, n)), jnp.float32)
                sync(A)
                kw = dict(precision="highest", pallas=True, norm="fast",
                          panel_impl="loop", trailing_precision=tprec)
                t0 = time.perf_counter()
                single = _blocked_qr_impl.lower(A, nb, **kw).compile()
                H, al = single(A)
                sync(al)

                def chained(A):
                    def body(C, _):
                        Hc, ac = _blocked_qr_impl(C, nb, **kw)
                        return Hc, ac[0]
                    return lax.scan(body, A, None, length=chain)

                ck = jax.jit(chained).lower(A).compile()
                compile_s = time.perf_counter() - t0
                _, s = ck(A)
                sync(s)

                def tmin(f, pick):
                    ts = []
                    for _ in range(repeats):
                        t0 = time.perf_counter()
                        r = f(A)
                        sync(pick(r))
                        ts.append(time.perf_counter() - t0)
                    return min(ts)

                t1 = tmin(single, lambda r: r[1])
                tk = tmin(ck, lambda r: r[1])
                t = (tk - t1) / (chain - 1)
                unreliable = not (tk > t1 * 1.05 and t > 0)
                if unreliable:
                    t = t1
                # Backward error on the SAME factorization that was timed.
                QR = _apply_q_impl(H, r_matrix(H, al), nb,
                                   precision="highest")
                berr = float(jnp.linalg.norm(QR - A) / jnp.linalg.norm(A))
                flops = (4.0 / 3.0) * n**3
                emit({"metric": f"qr_gflops_per_chip_f32_{n}x{n}",
                      "value": round(flops / t / 1e9, 2),
                      "unit": "GFLOP/s", "seconds": round(t, 4),
                      "block_size": nb,
                      "precision": "highest",
                      "trailing_precision": tprec or "highest",
                      "backward_error": berr,
                      "backward_error_target": 1e-5,
                      "margin_vs_target": round(1e-5 / max(berr, 1e-30), 1),
                      "chain_length": chain,
                      "seconds_single_dispatch": round(t1, 4),
                      "seconds_chain": round(tk, 4),
                      "compile_seconds": round(compile_s, 2),
                      "chain_unreliable": unreliable})
        except Exception as ex:
            emit({"metric": name, "ok": False,
                  "error": f"{type(ex).__name__}: {ex}"[:400]})

    # Smallest-first; baseline (tp=None) at each size right before the
    # split pair so the comparison shares cache/thermal conditions. nb per
    # auto_block_size's measured optimum (256 below 12288, 512 at 16384).
    stage(4096, 256, None, 25, 560)
    stage(4096, 256, "high", 25, 560)
    stage(8192, 256, None, 5, 560)
    stage(8192, 256, "high", 5, 560)
    stage(16384, 512, None, 3, 580, repeats=2)
    stage(16384, 512, "high", 3, 580, repeats=2)
    # Default-precision trailing ("default" = pure bf16 inputs) is the
    # aggressive end — measure it at one size for the error curve.
    stage(4096, 256, "default", 25, 560)
    _stage("done")


if __name__ == "__main__":
    main()
