"""Summarize the round-4 hardware artifacts into one decision table.

Reads benchmarks/results/tpu_r4_*.jsonl + bench_tpu_tee.jsonl (whatever
exists), prints:

* the headline candidates (size, nb, flat, TF/s) sorted by rate,
  accuracy-qualified rows only (backward error <= 1e-5 where reported);
* the split-panel verdict per size (flat 512 vs 256 vs 128; nb 512 vs
  1024) with the winner and margin;
* the trailing-precision pairs (rate delta + backward error vs target);
* the phase breakdown row and the c64-embedding rows, verbatim.

Pure reporting — makes the post-session default-flipping decisions
(PALLAS_FLAT_WIDTH, auto_block_size) reviewable at a glance.

Usage: python benchmarks/analyze_r4.py
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_RES = os.path.join(_HERE, "results")
sys.path.insert(0, os.path.dirname(_HERE))

from bench import _parse_round  # noqa: E402 — one parse rule, not two


def _round() -> int:
    # Same round tag as tpu_session_r4.sh / bench.py (all default to 5,
    # all strip an 'r'/'R' prefix): DHQR_ROUND=4 analyzes the round-4
    # artifacts that session would have written.
    return _parse_round(os.environ.get("DHQR_ROUND", "5"))


def _rows():
    rnd = _round()
    tag = f"r{rnd}"
    seen: set = set()
    for path in sorted(glob.glob(os.path.join(_RES, f"tpu_{tag}_*.jsonl"))) + \
            [os.path.join(_RES, f"bench_{tag}_run.jsonl"),
             os.path.join(_RES, "bench_tpu_tee.jsonl")]:
        if not os.path.exists(path):
            continue
        tee = os.path.basename(path) == "bench_tpu_tee.jsonl"
        with open(path) as f:
            for line in f:
                try:
                    r = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(r, dict):
                    continue
                # The tee artifact is append-only ACROSS rounds: keep only
                # rows stamped with the analyzed round (bench.py stamps
                # "round" since round 5). Unstamped tee rows predate the
                # stamp — they belong to rounds <= 4, so they are admitted
                # whenever a pre-stamp round is being analyzed (their
                # per-round origin is unrecoverable) and excluded from
                # round-5+ tables (ADVICE r4: a stale fast tee row must
                # not win a later round's decision table).
                if tee:
                    row_round = r.get("round", rnd if rnd <= 4 else None)
                    if row_round != rnd:
                        continue
                # Banked re-emits (DHQR_BENCH_SKIP_BANKED recovery
                # sessions re-print an earlier stage's row instead of
                # re-measuring) are provenance duplicates whose extra
                # "banked" flag defeats the content dedup below — the
                # original measurement is already in the tee.
                if r.get("banked"):
                    continue
                # One measurement can land in several artifacts (the
                # supervisor re-prints the child's teed headline into the
                # session's bench_${R}_run.jsonl) — dedup on content so a
                # duplicate cannot crowd the top-10 candidate table.
                key = json.dumps(r, sort_keys=True)
                if key in seen:
                    continue
                seen.add(key)
                r["_artifact"] = os.path.basename(path)
                yield r


def _errors(r) -> dict:
    """Measured backward errors only (never the _target constant)."""
    return {k: v for k, v in r.items()
            if k.startswith("backward_error") and not k.endswith("_target")
            and isinstance(v, (int, float))}


def _accurate(r) -> bool:
    return all(v <= 1e-5 for v in _errors(r).values())


def _qualified(r) -> bool:
    return _accurate(r) and r.get("trailing_precision") in (None, "highest")


def main() -> None:
    rows = list(_rows())
    if not rows:
        print(f"no tpu_r{_round()} artifacts yet")
        return

    qr = [r for r in rows
          if str(r.get("metric", "")).startswith("qr_gflops_per_chip_f32")
          and isinstance(r.get("value"), (int, float))
          and r.get("platform") == "tpu"
          and not r.get("chain_unreliable")]

    print("== headline candidates (accuracy-qualified, best first) ==")
    qualified = [r for r in qr if _qualified(r)]
    for r in sorted(qualified, key=lambda r: -r["value"])[:10]:
        size = re.search(r"(\d+)x\d+$", r["metric"]).group(1)
        # `or`-normalized: an explicit null in the row reaches .get() as
        # None, which would TypeError under the width format (ADVICE r4).
        # Schedule markers: an aggregated/lookahead row that tops the
        # table must not read as the default engine's headline.
        sched = ("" if not r.get("agg_panels") else
                 f" agg={r['agg_panels']}") + \
                ("" if not r.get("lookahead") else " lookahead") + \
                ("" if r.get("panel_impl") in ("loop", None) else
                 f" {r['panel_impl']}") + \
                ("" if not r.get("donate") else " donate")
        print(f"  {size:>6}  nb={r.get('block_size') or '?':>4} "
              f"flat={r.get('pallas_flat') or '-':>4} "
              f"{r['value']:>9.1f} GF/s{sched}   [{r['_artifact']}]")

    print("\n== split/width ladder by size ==")
    by_size: dict = {}
    for r in qr:
        if r.get("trailing_precision") not in (None, "highest"):
            continue  # tp-split rows are precision experiments, not
            # width candidates — they must not shadow the matched-
            # precision baseline sharing their (nb, flat) key
        size = int(re.search(r"(\d+)x\d+$", r["metric"]).group(1))
        key = (r.get("block_size"), r.get("pallas_flat"),
               bool(r.get("lookahead")), r.get("agg_panels"),
               r.get("panel_impl") or "loop", bool(r.get("donate")))
        cur = by_size.setdefault(size, {})
        if key not in cur or r["value"] > cur[key]["value"]:
            cur[key] = r
    for size in sorted(by_size):
        variants = by_size[size]
        # "best" must itself be a defensible default: accuracy-qualified
        # rows only (a fast disqualified config must not drive a
        # PALLAS_FLAT_WIDTH / auto_block_size flip).
        pool = [r for r in variants.values() if _qualified(r)] \
            or list(variants.values())
        best = max(pool, key=lambda r: r["value"])
        print(f"  {size}:")
        for (nb, flat, la, agg, pi, don), r in sorted(
                variants.items(), key=lambda kv: -kv[1]["value"]):
            mark = " <== best" if r is best else ""
            if not _qualified(r):
                mark = " (disqualified: accuracy)"
            tp = r.get("trailing_precision")
            tp_s = f" tp={tp}" if tp not in (None, "highest") else ""
            la_s = " lookahead" if la else ""
            agg_s = f" agg={agg}" if agg else ""
            pi_s = f" {pi}" if pi not in ("loop", None) else ""
            don_s = " donate" if don else ""
            print(f"    nb={nb} flat={flat or '-'}{tp_s}{la_s}{agg_s}{pi_s}"
                  f"{don_s}: {r['value']:.1f} GF/s{mark}")

    print("\n== trailing-precision pairs (baseline vs split, per size) ==")
    tp_rows = [r for r in rows if r.get("trailing_precision")]
    by_pair: dict = {}
    for r in tp_rows:
        m = re.search(r"(\d+)x\d+$", str(r.get("metric", "")))
        if m:
            by_pair.setdefault(int(m.group(1)), []).append(r)
    for size in sorted(by_pair):
        base = [r for r in by_pair[size]
                if r["trailing_precision"] == "highest"]
        for r in by_pair[size]:
            if r["trailing_precision"] == "highest":
                continue
            delta = ""
            if base and isinstance(r.get("value"), (int, float)):
                b = max(x["value"] for x in base
                        if isinstance(x.get("value"), (int, float)))
                delta = f", delta={100 * (r['value'] / b - 1):+.1f}%"
            print(f"  {size}: tp={r['trailing_precision']} "
                  f"{r.get('value')} GF/s{delta}, errors={_errors(r)}, "
                  f"target=1e-5, qualified={_accurate(r)}")

    print("\n== phase breakdown / embedding rows ==")
    for r in rows:
        m = str(r.get("metric", ""))
        if m.startswith("phase_breakdown") or m.startswith("c64_embed"):
            r2 = {k: v for k, v in r.items() if not k.startswith("_")}
            print(f"  {json.dumps(r2)}")

    failures = [r for r in rows if r.get("ok") is False]
    if failures:
        print("\n== failed stages ==")
        for r in failures:
            print(f"  {r.get('metric')}: {r.get('error')} "
                  f"[{r['_artifact']}]")


if __name__ == "__main__":
    main()
