"""CPU flop-overhead control for the round-5 schedule knobs.

The TPU upside of ``agg_panels`` is fewer wide trailing passes (fixed
per-pass cost); its downside is the extra aggregate-T flops. A CPU
timing at a flop-bound size isolates the DOWNSIDE: XLA-CPU has no MXU
pass structure to save, so the agg-vs-default CPU delta is an upper
bound on the pure extra-flop cost the TPU must amortize. Lookahead is
measured the same way (expected ~neutral: same flops, reordered).

Emits one JSON line per config into stdout (append to
``results/agg_cpu_control.jsonl`` via the shell). CPU-only by
construction — never touches the TPU relay.

Usage: python benchmarks/agg_cpu_control.py
"""

from __future__ import annotations

import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from _axon_env import default_to_virtual_cpu

default_to_virtual_cpu(n_devices=1, optin_env="DHQR_NEVER_SET")


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from dhqr_tpu.ops.blocked import _blocked_qr_impl
    from dhqr_tpu.utils.profiling import sync

    rng = np.random.default_rng(0)
    # Two regimes: (2048, 64) keeps the aggregate-T small relative to the
    # trailing work; (4096, 128) doubles the group width (W = k*nb up to
    # 512), where the extra aggregate-T flops should start to show.
    for n, nb in ((2048, 64), (4096, 128)):
        A = jnp.asarray(rng.random((n, n)), jnp.float32)
        flops = (4.0 / 3.0) * n**3

        def timed(**kw):
            c = _blocked_qr_impl.lower(A, nb, precision="highest",
                                       norm="fast", **kw).compile()
            H, al = c(A)
            sync(al)
            ts = []
            for _ in range(5):  # min-of-5: shared-host CPU jitter is
                t0 = time.perf_counter()  # easily +-10% run to run
                H, al = c(A)
                sync(al)
                ts.append(time.perf_counter() - t0)
            return min(ts)

        base = timed()
        rows = [{"schedule": "default", "seconds": round(base, 4)}]
        for k in (2, 4):
            t = timed(agg_panels=k)
            rows.append({"schedule": f"agg{k}", "seconds": round(t, 4),
                         "vs_default": round(t / base, 4)})
        t = timed(lookahead=True)
        rows.append({"schedule": "lookahead", "seconds": round(t, 4),
                     "vs_default": round(t / base, 4)})
        for r in rows:
            r.update({"metric": "qr_cpu_flop_control", "n": n,
                      "block_size": nb,
                      "gflops": round(flops / r["seconds"] / 1e9, 1),
                      "platform": "cpu"})
            print(json.dumps(r), flush=True)


if __name__ == "__main__":
    main()
