"""dhqr-armor acceptance: the zero-silent-garbage chaos grid.

The round-19 decision artifact (benchmarks/README "Round-19 decision
rules"): every sharded engine family x CPU topology P in {2, 4, 8} x
wire format in {f32, bf16, int8} x seeded fault schedule in
{clean, corrupt, nan, drop},

1. **zero silent garbage** — per cell, the dispatched result either
   verifies (solve families against the reference 8x-LAPACK
   normal-equations criterion; factor families against the armor
   weighted-checksum invariant at the wire format's tolerance), or the
   call resolves TYPED (`CorruptionDetected`/`ShardFailure` carrying
   the collective label and recovery path). A cell that returns an
   out-of-bar result untyped — detected or not — is silent garbage,
   and the committed grid has none;
2. **detection works** — the one-shot corrupt/nan schedules (the
   deterministic `:k` fire-on-kth-visit trigger) are detected and
   recovered by a single re-dispatch wherever they perturb the result
   (a corruption the math provably absorbs — CholeskyQR2's first Gram
   pass is a preconditioner — is recorded "benign", which is honesty,
   not a miss); the persistent drop schedule exhausts the ladder and
   resolves typed;
3. **armed overhead** — a warm armed loop holds >= 0.95x the disarmed
   loop (verification is O(mn) jitted reductions; the checked programs
   are THE disarmed programs) with ZERO recompiles
   (``jax.monitoring`` backend_compile events).

Ends with a ``serving_armor_verdict`` row the regress gate's
``armor-*`` rules enforce from then on.

Usage:  python benchmarks/serving_armor.py
Writes: benchmarks/results/serving_armor_<platform>.jsonl (append)
"""

from __future__ import annotations

import contextlib
import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

DEVICE_COUNTS = (2, 4, 8)
MODES = (None, "bf16", "int8")
#: (schedule name, site, (prob, count) or None). The one-shot
#: schedules use the round-19 :k segment so the SAME traced collective
#: is corrupted on every replay — the k itself is PER-FAMILY (each
#: engine's k_default in families(): the interesting collective sits
#: at a different visit index per engine) and drop pins k=1; drop is
#: persistent (count=None) — it re-fires on every recovery re-trace,
#: which is what drives the ladder to its typed refusal.
SCHEDULES = (
    ("clean", None, None),
    ("corrupt", "parallel.collective.corrupt", (1.0, 1)),
    ("nan", "parallel.collective.nan", (1.0, 1)),
    ("drop", "parallel.collective.drop", (1.0, None)),
)
WARM_ITERS = 40


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main() -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    rnd = int(os.environ.get("DHQR_ROUND", "19"))
    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import monitoring

    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(_REPO, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

    from bench import SCHEMA_VERSION, _Watchdog

    compiles = {"n": 0}
    monitoring.register_event_duration_secs_listener(
        lambda name, *a, **k: compiles.__setitem__("n", compiles["n"] + 1)
        if name == "/jax/core/compile/backend_compile_duration" else None)

    from dhqr_tpu import armor
    from dhqr_tpu.faults import injected
    from dhqr_tpu.models.qr_model import lstsq as model_lstsq
    from dhqr_tpu.obs import metrics as obs_metrics
    from dhqr_tpu.parallel.mesh import column_mesh
    from dhqr_tpu.parallel.sharded_cholqr import sharded_cholqr_lstsq
    from dhqr_tpu.parallel.sharded_qr import (
        sharded_blocked_qr,
        sharded_householder_qr,
    )
    from dhqr_tpu.parallel.sharded_solve import sharded_lstsq
    from dhqr_tpu.parallel.sharded_tsqr import row_mesh, sharded_tsqr_lstsq
    from dhqr_tpu.utils.config import ArmorConfig, FaultConfig
    from dhqr_tpu.utils.profiling import sync
    from dhqr_tpu.utils.testing import (
        TOLERANCE_FACTOR,
        normal_equations_residual,
        oracle_residual,
    )

    _stage("backend_init")
    with _Watchdog("backend_init", 240):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    out_path = os.path.join(_REPO, "benchmarks", "results",
                            f"serving_armor_{platform}.jsonl")
    navail = len(jax.devices())
    counts = tuple(p for p in DEVICE_COUNTS if p <= navail)
    if not counts:
        print("serving_armor: SKIPPED (needs >= 2 devices; set XLA_FLAGS="
              "--xla_force_host_platform_device_count=8 before the first "
              "backend touch)", file=sys.stderr, flush=True)
        return

    def emit(rec):
        rec.update(platform=platform, device_kind=kind, round=rnd,
                   schema_version=SCHEMA_VERSION)
        line = json.dumps(rec)
        print(line, flush=True)
        with open(out_path, "a") as f:
            f.write(line + "\n")

    rng = np.random.default_rng(0)

    def problems(P):
        n, nb = 8 * P, 4
        m = 2 * n
        mt, nt = 32 * P, 8
        A = jnp.asarray(rng.random((m, n)), jnp.float32)
        b = jnp.asarray(rng.random(m), jnp.float32)
        At = jnp.asarray(rng.random((mt, nt)), jnp.float32)
        bt = jnp.asarray(rng.random(mt), jnp.float32)
        return dict(P=P, n=n, nb=nb, cmesh=column_mesh(P),
                    rmesh=row_mesh(P), A=A, b=b, At=At, bt=bt,
                    ref=oracle_residual(np.asarray(A), np.asarray(b)),
                    ref_t=oracle_residual(np.asarray(At), np.asarray(bt)))

    def families(ctx):
        """(family, comms -> result, in_bar(result, comms)) per engine.
        Solve families check the 8x-LAPACK bar; factor families check
        the armor checksum invariant at the wire tolerance — an
        out-of-bar factor IS what a downstream solve would consume."""
        nb = ctx["nb"]

        def qr_bar(out, c, matrix):
            gap, _ = armor.checks.qr_gap(out[0], out[1], matrix,
                                         min(32, matrix.shape[1]))
            return gap <= (1e-4 if c is None else armor.WIRE_RTOL)

        def x_bar(x, problem, ref):
            res = normal_equations_residual(problem[0], np.asarray(x),
                                            problem[1])
            return bool(res < TOLERANCE_FACTOR * ref)

        yield ("unblocked_qr",
               lambda c: sharded_householder_qr(ctx["A"], ctx["cmesh"],
                                                comms=c),
               lambda out, c: qr_bar(out, c, ctx["A"]),
               1)   # fori-loop body: ONE traced collective -> k=1
        yield ("blocked_qr",
               lambda c: sharded_blocked_qr(ctx["A"], ctx["cmesh"],
                                            block_size=nb, comms=c),
               lambda out, c: qr_bar(out, c, ctx["A"]),
               2)
        # The column-engine solve carries its compressed-mode CSNE
        # recovery at the MODEL tier (PR-13 contract: qr_model floors
        # refine per wire format), so compressed cells route there —
        # same split serving_wire.py uses; f32 cells run the raw
        # engine pipeline.
        yield ("sharded_lstsq",
               lambda c: (sharded_lstsq(ctx["A"], ctx["b"], ctx["cmesh"],
                                        block_size=nb)
                          if c is None else
                          model_lstsq(ctx["A"], ctx["b"],
                                      mesh=ctx["cmesh"], block_size=nb,
                                      comms=c)),
               lambda x, c: x_bar(x, (ctx["A"], ctx["b"]), ctx["ref"]),
               2)
        yield ("tsqr_lstsq",
               lambda c: sharded_tsqr_lstsq(ctx["At"], ctx["bt"],
                                            ctx["rmesh"], block_size=8,
                                            comms=c),
               lambda x, c: x_bar(x, (ctx["At"], ctx["bt"]),
                                  ctx["ref_t"]),
               2)
        yield ("cholqr_lstsq",
               lambda c: sharded_cholqr_lstsq(ctx["At"], ctx["bt"],
                                              ctx["rmesh"], comms=c),
               lambda x, c: x_bar(x, (ctx["At"], ctx["bt"]),
                                  ctx["ref_t"]),
               3)   # the 3rd psum (Q^H b): Gram-pass hits are absorbed
                    # by CholeskyQR2's second pass (a preconditioner)

    # ---- phase 1: the chaos grid ----------------------------------------
    _stage("chaos_grid")
    cells = 0
    silent_garbage = 0
    fault_cells = 0
    covered = 0   # faulted cells that detected, typed, or stayed in bar
    not_fired = 0  # faulted cells whose schedule never fired (drift)
    totals = {"detections": 0, "recovered_redispatch": 0,
              "recovered_degrade": 0, "typed_failures": 0,
              "verifications": 0}
    for P in counts:
        ctx = problems(P)
        for family, run, in_bar, k_default in families(ctx):
            for comms in MODES:
                # int8 on the cholqr Gram degrades to bf16 at the seam
                # (documented); the cell still runs — that IS the mode.
                for sched, site, spec in SCHEDULES:
                    armor.reset_wire_trips()
                    state = armor.arm(ArmorConfig(enabled=True))
                    scope = contextlib.nullcontext()
                    if site is not None:
                        prob, cnt = spec
                        kth = k_default if sched != "drop" else 1
                        scope = injected(FaultConfig(
                            sites=((site, prob, cnt, kth),), seed=P))
                    outcome, typed_as, label = "clean", None, None
                    ok_bar = None
                    try:
                        with scope as harness:
                            out = run(comms)
                            jax.block_until_ready(
                                jax.tree_util.tree_leaves(out))
                            fired = 0 if site is None else \
                                harness.stats()[site]["fired"]
                        ok_bar = bool(in_bar(out, comms))
                        snap = state.metrics_snapshot()
                        if site is None:
                            outcome = "clean"
                        elif snap["detections"] > 0:
                            outcome = "recovered"
                        elif fired and ok_bar:
                            outcome = "benign"  # math absorbed the hit
                        elif not fired:
                            outcome = "not_fired"
                        else:
                            outcome = "UNDETECTED"
                        if not ok_bar:
                            silent_garbage += 1
                    except armor.ArmorError as e:
                        outcome, typed_as = "typed", type(e).__name__
                        label = e.label
                        snap = state.metrics_snapshot()
                    finally:
                        armor.disarm()
                    for key in totals:
                        totals[key] += snap.get(key, 0)
                    cells += 1
                    if site is not None:
                        fault_cells += 1
                        # "not_fired" is NOT covered: a schedule whose
                        # :k index drifted past the program's
                        # collectives means the grid stopped exercising
                        # detection — that must fail the verdict, not
                        # read as a pass.
                        if outcome == "not_fired":
                            not_fired += 1
                        elif outcome in ("recovered", "typed", "benign"):
                            covered += 1
                    emit({"metric": "serving_armor", "phase": "cell",
                          "family": family, "P": P,
                          "comms": comms or "f32", "schedule": sched,
                          "outcome": outcome, "typed_as": typed_as,
                          "label": label, "in_bar": ok_bar,
                          "detections": snap.get("detections", 0),
                          "recovered_redispatch":
                              snap.get("recovered_redispatch", 0),
                          "recovered_degrade":
                              snap.get("recovered_degrade", 0)})
    armor.reset_wire_trips()

    # ---- phase 2: armed overhead + zero warm recompiles ------------------
    # The overhead problem is sized like a real serving dispatch (the
    # chaos grid's 8P-column toys are detection vehicles): at 512x128
    # the O(mn) verification reductions amortize against the O(mn^2)
    # dispatch the way they do on any production shape — the ≤5% bar
    # is a statement about dispatches worth sharding, not about
    # sub-millisecond toys where one device fetch dominates anything.
    _stage("overhead")
    P_ov = max(counts)
    n_ov, nb_ov = 16 * P_ov, 16
    m_ov = 4 * n_ov
    A_ov = jnp.asarray(rng.random((m_ov, n_ov)), jnp.float32)
    b_ov = jnp.asarray(rng.random(m_ov), jnp.float32)
    cmesh_ov = column_mesh(P_ov)

    def loop():
        # Fenced per dispatch, deliberately: letting the async stream
        # pile up unfenced collectives of one program deadlocked the
        # XLA CPU rendezvous on this topology (participants waiting
        # forever), and the armed path fences per dispatch anyway (the
        # verification reads the result) — fencing both sides measures
        # like-for-like.
        t0 = time.perf_counter()
        for _ in range(WARM_ITERS):
            jax.block_until_ready(
                sharded_lstsq(A_ov, b_ov, cmesh_ov, block_size=nb_ov))
        return time.perf_counter() - t0

    # Alternating interleaved A/B, median-of-5 after settle passes
    # (the PR-9 overhead-measurement pattern): back-to-back blocks on a
    # contended shared CPU drift by more than the effect being
    # measured, interleaving cancels the drift.
    import statistics

    loop()                              # compile, disarmed
    armor.arm(ArmorConfig(enabled=True))
    loop()                              # compile the armed checks
    pre = compiles["n"]
    armor.disarm()
    dis_samples, arm_samples = [], []
    for _ in range(5):
        armor.disarm()
        dis_samples.append(loop())
        armor.arm(ArmorConfig(enabled=True))
        arm_samples.append(loop())
    warm_recompiles = compiles["n"] - pre
    armor.disarm()
    armed_over_disarmed = (statistics.median(dis_samples)
                           / statistics.median(arm_samples))
    emit({"metric": "serving_armor", "phase": "warm_armed",
          "armed_over_disarmed": round(armed_over_disarmed, 4),
          "warm_recompiles": warm_recompiles,
          "iters": WARM_ITERS, "m": m_ov, "n": n_ov, "P": P_ov})

    # ---- verdict ---------------------------------------------------------
    _stage("verdict")
    ok = (silent_garbage == 0 and covered == fault_cells
          and not_fired == 0
          and armed_over_disarmed >= 0.95 and warm_recompiles == 0)
    verdict = {"metric": "serving_armor_verdict", "ok": bool(ok),
               "cells": cells, "fault_cells": fault_cells,
               "zero_silent_garbage": silent_garbage == 0,
               "all_faults_detected_or_typed": covered == fault_cells,
               "not_fired_cells": not_fired,
               "armed_over_disarmed": round(armed_over_disarmed, 4),
               "warm_recompiles": warm_recompiles}
    # Session-wide armor accounting rides flat on the verdict row (the
    # PR-11 registry-stamp pattern; the per-cell states are summed
    # here because each cell armed a fresh seam).
    for key, val in totals.items():
        verdict[f"armor.{key}"] = val
    emit(verdict)
    if not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
