"""Benchmark suite for the five BASELINE.md configs.

Prints one JSON line per config:
    {"config": k, "metric": "...", "value": V, "unit": "GFLOP/s", ...}

The five configs (BASELINE.md "Targets for the new TPU framework"):
  1. 1024x1024 Float64 dense QR, single device (CPU-reference scale)
  2. tall-skinny 65536x256 Float32 lstsq via TSQR, row-sharded
  3. square 16384x16384 Float32 QR, 1-D column-cyclic
  4. blocked compact-WY (nb=128) 32768x4096 Float32
  5. overdetermined least-squares 131072x512 via QR + back-substitution

The nominal sizes assume multi-chip pods (v4-8..v5p-32). On smaller hardware
run with ``--scale S`` (divides m and n by S, default chosen to fit a single
chip) or pick configs with ``--configs 1,5``. Mesh size adapts to visible
devices; config 3 uses the cyclic layout, the others block layout.

Usage:
    python benchmarks/run.py [--configs 1,2,3,4,5] [--scale 4] [--repeats 3]

The reference has no benchmarks directory at all (SURVEY.md §6); its only
perf artifact is runtime ratio prints in the tests (runtests.jl:84-89),
which ``python -m dhqr_tpu.harness --bench`` reproduces.
"""

from __future__ import annotations

import argparse
import json
import time


def _flops_qr(m: float, n: float) -> float:
    return 2.0 * m * n * n - (2.0 / 3.0) * n**3


def _flops_lstsq(m: float, n: float) -> float:
    return _flops_qr(m, n) + 4.0 * m * n + n * n


def _bench(fn, sync, repeats: int):
    out = fn()
    sync(out)  # compile + warm
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn()
        sync(out)
        times.append(time.perf_counter() - t0)
    return min(times), out


def main(argv=None) -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--configs", default="1,2,3,4,5")
    parser.add_argument("--scale", type=int, default=None,
                        help="divide problem dims by this (default: fit 1 chip)")
    parser.add_argument("--repeats", type=int, default=3)
    parser.add_argument("--block-size", type=int, default=128)
    parser.add_argument(
        "--engine", default=None,
        choices=["tsqr", "cholqr2", "cholqr3"],
        help="override the lstsq engine for configs 2 and 5 "
        "(default: config 2 uses tsqr, config 5 householder)",
    )
    args = parser.parse_args(argv)

    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

    # Hardware needs explicit opt-in (DHQR_BENCH_TPU=1 or JAX_PLATFORMS
    # naming tpu): ambient axon + a wedged relay would hang the first
    # backend touch (round-4 hardening; shared recipe in _axon_env).
    from _axon_env import default_to_virtual_cpu

    forced_virtual = default_to_virtual_cpu(8)

    import jax

    from dhqr_tpu.utils.platform import (
        cpu_requested,
        enable_compile_cache,
        force_cpu_platform,
    )

    if cpu_requested():
        force_cpu_platform()
    enable_compile_cache()
    import jax.numpy as jnp
    import numpy as np

    import dhqr_tpu
    from dhqr_tpu.ops.blocked import _apply_q_impl
    from dhqr_tpu.ops.solve import r_matrix
    from dhqr_tpu.parallel.mesh import column_mesh
    from dhqr_tpu.utils.profiling import sync

    from dhqr_tpu.utils.testing import (
        TOLERANCE_FACTOR,
        normal_equations_residual,
        oracle_residual,
    )

    platform = jax.default_backend()
    ndev = len(jax.devices())
    if platform == "cpu":
        jax.config.update("jax_enable_x64", True)
    # default scale: nominal sizes target pods; a single chip gets /4 —
    # and so does a FORCED virtual mesh (8 host-thread "devices" are not
    # a pod; without this, a bare CPU invocation would attempt the
    # nominal 16384^2-class problems at scale=1).
    scale = args.scale if args.scale is not None else (
        1 if ndev >= 8 and not forced_virtual else 4)
    nb = args.block_size
    rng = np.random.default_rng(0)

    # BASELINE.md backward-error target for the QR configs (north star:
    # ||QR - A|| / ||A|| < 1e-5 at f32; f64 gets the same bound, which it
    # beats by ~10 decades — the point is a recorded pass, not a tight one).
    BERR_TARGET = 1e-5

    def qr_accuracy(A, H, alpha):
        """Judgeable accuracy record for a QR config (VERDICT r3 weak #4:
        a number with no criterion next to it is unjudgeable)."""
        m_, n_ = A.shape
        R = r_matrix(H, alpha)  # (n, n); Q applies to m-row operands, so
        # pad: Q @ [R; 0] = the m x n product QR for tall A.
        B = jnp.concatenate([R, jnp.zeros((m_ - n_, n_), R.dtype)]) \
            if m_ > n_ else R
        QR = _apply_q_impl(H, B, nb)
        berr = float(jnp.linalg.norm(QR - A) / jnp.linalg.norm(A))
        return {"backward_error": berr, "backward_error_target": BERR_TARGET,
                "pass": bool(berr < BERR_TARGET)}

    def lstsq_accuracy(A, b, x):
        """8x LAPACK-oracle criterion for an lstsq config — the exact
        reference acceptance rule (runtests.jl:49-51,62,81)."""
        res = normal_equations_residual(A, np.asarray(x), b)
        ref = oracle_residual(np.asarray(A), np.asarray(b))
        return {"normal_eq_residual": res, "oracle_residual": ref,
                "tolerance": TOLERANCE_FACTOR * ref,
                "pass": bool(res < TOLERANCE_FACTOR * ref)}

    def mesh_or_none(max_devices=None):
        usable = ndev if max_devices is None else min(ndev, max_devices)
        return column_mesh(usable) if usable > 1 else None

    def report(k, name, m, n, seconds, flops, extra=None):
        rec = {
            "config": k,
            "metric": name,
            "value": round(flops / seconds / 1e9, 2),
            "unit": "GFLOP/s",
            "seconds": round(seconds, 4),
            "shape": f"{m}x{n}",
            "platform": platform,
            "devices": ndev,
            "scale": scale,
        }
        rec.update(extra or {})
        print(json.dumps(rec))

    chosen = {int(tok) for tok in args.configs.split(",")}
    # The stage catalogue — config numbers, metric stems, nominal
    # (pod-scale) shapes, default engines/layouts — is the route
    # registry's (tune/registry.BENCH_STAGES, round 21): each stage
    # names the registered route it exercises and the atlas pass
    # (DHQR505) fails lint if a stage drifts off the registry. The
    # imperative bodies below stay here — they ARE the benchmark.
    from dhqr_tpu.tune.registry import bench_stages

    stages = {s.config: s for s in bench_stages()}

    if 1 in chosen:
        s = stages[1]
        # f64 runs where f64 is native; on TPU it is emulated, so report f32
        dt = jnp.float64 if platform == "cpu" else jnp.float32
        m = n = s.m // (scale if platform == "cpu" else 1)
        A = jnp.asarray(rng.random((m, n)), dtype=dt)
        t, (H, alpha) = _bench(
            lambda: dhqr_tpu.blocked_householder_qr(A, nb), sync, args.repeats
        )
        report(s.config, f"{s.metric}_{jnp.dtype(dt).name}", m, n, t,
               _flops_qr(m, n), qr_accuracy(A, H, alpha))

    if 2 in chosen:
        s = stages[2]
        # tall-skinny: TSQR (row-parallel, one all-gather) — the regime where
        # the column layout cannot scale (see dhqr_tpu/parallel/sharded_tsqr.py)
        m, n = s.m // scale, s.n // scale
        A = jnp.asarray(rng.random((m, n)), dtype=jnp.float32)
        b = jnp.asarray(rng.random(m), dtype=jnp.float32)
        eng2 = args.engine or s.engine
        if ndev > 1 and m % ndev == 0 and (eng2 != "tsqr" or m // ndev >= n):
            from dhqr_tpu.parallel.sharded_tsqr import row_mesh
            rmesh = row_mesh(ndev)
            fn = lambda: dhqr_tpu.lstsq(A, b, mesh=rmesh, engine=eng2,
                                        block_size=nb)
            meshsz = ndev
        else:
            fn = lambda: dhqr_tpu.lstsq(A, b, engine=eng2, block_size=nb)
            meshsz = 1
        t, x2 = _bench(fn, sync, args.repeats)
        report(s.config,
               s.metric.replace("_lstsq", f"_{eng2}_lstsq") + "_f32",
               m, n, t, _flops_lstsq(m, n),
               {"mesh": meshsz, **lstsq_accuracy(A, b, x2)})

    if 3 in chosen:
        s = stages[3]
        m = n = s.m // scale
        mesh = mesh_or_none()
        # the cyclic layout needs n % (nb * P) == 0; fall back to a single
        # device rather than dying on an awkward device count (ADVICE r1)
        nb3 = nb
        if mesh is not None:
            P = mesh.shape["cols"]
            nb3 = min(nb, n // P)
            if n % P or nb3 < 1 or (n // P) % nb3:
                mesh = None
        A = jnp.asarray(rng.random((m, n)), dtype=jnp.float32)
        if mesh is None:
            fn = lambda: dhqr_tpu.blocked_householder_qr(A, nb)
            layout = "single"
        else:
            from dhqr_tpu.parallel.sharded_qr import sharded_blocked_qr
            # pass the clamped width so the guard above and the engine agree
            fn = lambda: sharded_blocked_qr(A, mesh, block_size=nb3,
                                            layout=s.layout)
            layout = s.layout
        t, (H3, a3) = _bench(fn, sync, args.repeats)
        report(s.config, s.metric, m, n, t, _flops_qr(m, n),
               {"layout": layout, **qr_accuracy(A, H3, a3)})

    if 4 in chosen:
        s = stages[4]
        m, n = s.m // scale, s.n // scale
        A = jnp.asarray(rng.random((m, n)), dtype=jnp.float32)
        t, (H4, a4) = _bench(
            lambda: dhqr_tpu.blocked_householder_qr(A, nb), sync, args.repeats
        )
        report(s.config, s.metric, m, n, t, _flops_qr(m, n),
               {"block_size": nb, **qr_accuracy(A, H4, a4)})

    if 5 in chosen:
        s = stages[5]
        m, n = s.m // scale, s.n // scale
        mesh = mesh_or_none()
        if mesh is not None and n % mesh.shape["cols"]:
            n += mesh.shape["cols"] - n % mesh.shape["cols"]
        A = jnp.asarray(rng.random((m, n)), dtype=jnp.float32)
        b = jnp.asarray(rng.random(m), dtype=jnp.float32)
        if args.engine:
            rmesh5 = mesh
            if rmesh5 is not None and m % rmesh5.shape["cols"]:
                rmesh5 = None  # row engines need m divisible instead
            fn = lambda: dhqr_tpu.lstsq(A, b, mesh=rmesh5, engine=args.engine,
                                        block_size=nb)
        else:
            fn = lambda: dhqr_tpu.lstsq(A, b, mesh=mesh, block_size=nb)
        t, x = _bench(fn, sync, args.repeats)
        eff_mesh = rmesh5 if args.engine else mesh
        report(s.config, s.metric, m, n, t, _flops_lstsq(m, n),
               {"engine": args.engine or s.engine,
                "mesh": 1 if eff_mesh is None else eff_mesh.shape["cols"],
                **lstsq_accuracy(A, b, x)})


if __name__ == "__main__":
    main()
