"""dhqr-pulse acceptance: measured collectives + skew for every sharded engine.

The round-16 tentpole's decision artifact, mirroring the serving_xray
methodology (armed capture phase, then alternating interleaved A/B
median-of-5 warm overhead after settle passes):

* ``pulse_table`` — every sharded engine family (unblocked_qr,
  blocked_qr, sharded_solve, tsqr_lstsq, cholqr_lstsq) dispatched at
  every CPU topology in {2, 4, 8} with pulse capture ARMED: one row
  per :class:`~dhqr_tpu.obs.pulse.PulseReport` carrying the measured
  per-collective-family timing + launch counts, the traced analytic
  census, the per-shard skew spread, and the DHQR306
  measured-vs-analytic verdict (``skip`` WITH reason on CPU — no
  published interconnect — which is exactly the degradation contract;
  a TPU replay of this same script closes the wire check from the
  utils/platform ICI table);
* ``warm_disarmed`` / ``warm_armed`` — warm sharded-dispatch
  throughput with pulse disarmed vs ARMED (labels already measured,
  so the armed path is one store lookup per dispatch). Acceptance:
  armed costs <= 5% (median ratio >= 0.95), zero re-measures and zero
  backend recompiles on the armed passes (counted via
  ``jax.monitoring``'s backend_compile events);
* ``verdict`` — every family x topology captured
  (measured-or-reasoned-null), every DHQR306 green, the overhead bar,
  and the live ``comms.*`` registry snapshot stamped alongside.

Usage:  python benchmarks/serving_pulse.py [warm_repeats]
Writes: benchmarks/results/serving_pulse_<platform>.jsonl (append).
"""

from __future__ import annotations

import json
import os
import signal
import statistics
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# The multi-device CPU topology must be forced BEFORE the first
# backend touch (XLA_FLAGS is read once, at init) — the comms-audit
# convention. Harmless on real TPU hosts (the flag only shapes the
# host platform).
if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

DEVICE_COUNTS = (2, 4, 8)
WARM_REPEATS = 5          # median-of per arm (serving_obs methodology)
WARM_DISPATCHES = 20      # dispatches per warm pass


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main(warm_repeats: int = WARM_REPEATS) -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    from bench import ROUND, SCHEMA_VERSION, _Watchdog

    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import monitoring

    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(_REPO, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

    compiles = {"n": 0}
    monitoring.register_event_duration_secs_listener(
        lambda name, *a, **k: compiles.__setitem__(
            "n", compiles["n"] + 1)
        if name == "/jax/core/compile/backend_compile_duration" else None)

    from dhqr_tpu.obs import pulse, registry
    from dhqr_tpu.parallel.mesh import column_mesh
    from dhqr_tpu.parallel.sharded_cholqr import sharded_cholqr_lstsq
    from dhqr_tpu.parallel.sharded_qr import (
        sharded_blocked_qr,
        sharded_householder_qr,
    )
    from dhqr_tpu.parallel.sharded_solve import sharded_solve
    from dhqr_tpu.parallel.sharded_tsqr import row_mesh, sharded_tsqr_lstsq
    from dhqr_tpu.utils.profiling import sync

    _stage("backend_init")
    with _Watchdog("backend_init", 240):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    out_path = os.path.join(_REPO, "benchmarks", "results",
                            f"serving_pulse_{platform}.jsonl")
    navail = len(jax.devices())
    counts = tuple(p for p in DEVICE_COUNTS if p <= navail)

    def emit(rec):
        rec.update(platform=platform, device_kind=kind, round=ROUND,
                   schema_version=SCHEMA_VERSION)
        line = json.dumps(rec)
        print(line, flush=True)
        with open(out_path, "a") as f:
            f.write(line + "\n")

    rng = np.random.default_rng(0)

    def engine_dispatches(P: int):
        """(family, thunk) per sharded engine family at mesh size P —
        the dhqr-audit engine matrix, dispatched for real. Shapes are
        tiny on purpose: pulse measures collective structure and
        skew, not GEMM throughput."""
        n, nb = 8 * P, 4
        m = 2 * n
        cmesh = column_mesh(P)
        rmesh = row_mesh(P)
        A = jnp.asarray(rng.random((m, n)), jnp.float32)
        b = jnp.asarray(rng.random(m), jnp.float32)
        At = jnp.asarray(rng.random((16 * P, 8)), jnp.float32)
        bt = jnp.asarray(rng.random(16 * P), jnp.float32)
        H, alpha = sharded_blocked_qr(A, cmesh, block_size=nb)
        H, alpha = jax.block_until_ready((H, alpha))
        yield ("unblocked_qr",
               lambda: sharded_householder_qr(A, cmesh))
        yield ("blocked_qr",
               lambda: sharded_blocked_qr(A, cmesh, block_size=nb))
        yield ("sharded_solve",
               lambda: sharded_solve(H, alpha, b, cmesh, block_size=nb))
        yield ("tsqr_lstsq",
               lambda: sharded_tsqr_lstsq(At, bt, rmesh, block_size=8))
        yield ("cholqr_lstsq",
               lambda: sharded_cholqr_lstsq(At, bt, rmesh))

    # ---- capture phase: the full engine x topology matrix, armed ----
    _stage("capture_matrix")
    families_seen = []
    with _Watchdog("capture_matrix", 2400):
        store = pulse.arm(max_reports=256)
        for P in counts:
            for family, thunk in engine_dispatches(P):
                out = thunk()
                jax.block_until_ready(out)
                families_seen.append((family, P))
        pulse.disarm()
    reports = store.reports()
    emit({"metric": "serving_pulse", "phase": "capture",
          "topologies": list(counts), "families": len(families_seen),
          "captured": store.stats()["captures"],
          "unsupported": store.stats()["unsupported"],
          "store": store.stats()})
    for rep in reports:
        emit({"metric": "serving_pulse", "phase": "pulse_table",
              "captured": bool(rep.measured is not None
                               or rep.measured_unavailable),
              "dhqr306_pass": rep.dhqr306_pass,
              "pulse": rep.to_json()})

    # ---- warm overhead: disarmed vs armed (labels already measured) --
    Pw = counts[-1]
    warm = list(engine_dispatches(Pw))[:3]  # representative trio

    def warm_pass_rps() -> float:
        t0 = time.perf_counter()
        for _ in range(WARM_DISPATCHES):
            for _family, thunk in warm:
                jax.block_until_ready(thunk())
        return (WARM_DISPATCHES * len(warm)) / (
            time.perf_counter() - t0)

    _stage("warm_ladder")
    with _Watchdog("warm_ladder", 2400):
        # Settle passes (serving_obs methodology): drift the
        # post-compile throttle out of both arms. Also measures the
        # warm labels once so the armed arm never captures.
        pulse.arm(store=store)
        warm_pass_rps()
        pulse.disarm()
        warm_pass_rps()
        disarmed, armed = [], []
        captures_before = store.stats()["captures"]
        compiles_before = compiles["n"]
        for rep_i in range(warm_repeats):
            def one_armed() -> float:
                pulse.arm(store=store)
                try:
                    return warm_pass_rps()
                finally:
                    pulse.disarm()
            if rep_i % 2 == 0:
                disarmed.append(warm_pass_rps())
                armed.append(one_armed())
            else:
                armed.append(one_armed())
                disarmed.append(warm_pass_rps())
        recaptures_armed = store.stats()["captures"] - captures_before
        recompiles_armed = compiles["n"] - compiles_before
        overhead_ratio = statistics.median(armed) / statistics.median(
            disarmed)
    emit({"metric": "serving_pulse", "phase": "warm_disarmed",
          "dispatches_per_s": [round(r, 1) for r in disarmed],
          "median_rps": round(statistics.median(disarmed), 1)})
    emit({"metric": "serving_pulse", "phase": "warm_armed",
          "dispatches_per_s": [round(r, 1) for r in armed],
          "median_rps": round(statistics.median(armed), 1),
          "armed_over_disarmed": round(overhead_ratio, 4),
          "recaptures_armed": recaptures_armed,
          "recompiles_armed": recompiles_armed})

    # ---- verdict ----------------------------------------------------
    pulse.arm(store=store)        # live comms.* snapshot for the row
    comms_metrics = {k: v for k, v in registry().snapshot().items()
                     if k.startswith("comms.")}
    pulse.disarm()
    table_ok = bool(reports) and all(
        (r.measured is not None or r.measured_unavailable)
        for r in reports)
    measured_ok = all(r.measured is not None for r in reports
                      if r.n_devices >= 2
                      and not r.label.startswith("serve:"))
    dhqr306_ok = all(r.dhqr306_pass for r in reports)
    skew_ok = all(r.skew is not None or r.skew_unavailable
                  for r in reports)
    every_family = store.stats()["captures"] >= len(families_seen)
    ok = (overhead_ratio >= 0.95 and recaptures_armed == 0
          and recompiles_armed == 0 and table_ok and measured_ok
          and dhqr306_ok and skew_ok and every_family)
    verdict_row = {
        "metric": "serving_pulse_verdict",
        "armed_over_disarmed": round(overhead_ratio, 4),
        "armed_within_5pct": overhead_ratio >= 0.95,
        "zero_recaptures_armed": recaptures_armed == 0,
        "zero_recompiles_armed": recompiles_armed == 0,
        "every_family_captured": every_family,
        "every_report_measured_or_reasoned": table_ok,
        "multidevice_reports_measured": measured_ok,
        "dhqr306_all_green": dhqr306_ok,
        "skew_captured_or_reasoned": skew_ok,
        "families": len(families_seen),
        "topologies": list(counts),
        "ok": bool(ok),
    }
    # The live comms.* registry names ride FLAT on the verdict row so
    # the regress gate's field selectors can bound them directly.
    verdict_row.update(comms_metrics)
    emit(verdict_row)
    _stage("done")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else WARM_REPEATS)
