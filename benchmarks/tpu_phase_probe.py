"""Round-3 TPU probe: phase attribution + solve-side data.

1. **Panel-kernel fraction** — chain-time the fused Pallas panel alone at
   the production shapes ((12288, 512), (12288, 256), (4096, 256)) and
   compare against the full-QR stage times. If the serial in-kernel
   column sweep is a large fraction at nb=512, a two-level in-kernel
   panel (sub-panels + compact-WY interior GEMMs) is the next perf
   frontier; if small, the engine is trailing-GEMM-bound as designed and
   kernel work would be wasted.
   Panel flop model: sum_j 2*(nb - j)*m ~= 2*m*nb^2 (dots + rank-1s,
   masked rows do no useful work but are executed anyway — the model
   counts USEFUL flops so the number is comparable to the QR accounting).

2. **Solve-side data** — multi-RHS lstsq (k=64) and refine=1 vs refine=0
   at 4096^2, chain-timed: what does a solve cost next to the
   factorization, and what does one refinement sweep add?

Run ONE instance at a time (the axon relay allows a single TPU process).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main() -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    from bench import _Watchdog

    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from dhqr_tpu.ops.pallas_panel import _panel_qr_pallas_impl
    from dhqr_tpu.utils.profiling import sync

    _stage("backend_init")
    with _Watchdog("backend_init", 150):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    rng = np.random.default_rng(0)

    def emit(rec):
        rec["platform"] = platform
        rec["device_kind"] = kind
        print(json.dumps(rec), flush=True)

    def chain_min(single, chained, chain, repeats=3):
        def tmin(f):
            s = f()
            sync(s)
            ts = []
            for _ in range(repeats):
                t0 = time.perf_counter()
                s = f()
                sync(s)
                ts.append(time.perf_counter() - t0)
            return min(ts)

        t1, tk = tmin(single), tmin(chained)
        t = (tk - t1) / (chain - 1)
        unreliable = not (tk > t1 * 1.05 and t > 0)
        return (t1 if unreliable else t), t1, tk, unreliable

    # ---- 1. panel-kernel chain timing ----
    def panel_stage(m, nb, chain=25, watchdog=300):
        name = f"panel_chain_{m}x{nb}"
        _stage(name)
        try:
            with _Watchdog(name, watchdog):
                P = jnp.asarray(rng.standard_normal((m, nb)), jnp.float32)
                sync(P)

                single = jax.jit(
                    lambda P: _panel_qr_pallas_impl(P, 0)[1][0]
                ).lower(P).compile()

                def chained(P):
                    def body(C, _):
                        pf, al = _panel_qr_pallas_impl(C, 0)
                        return pf, al[0]
                    _, s = lax.scan(body, P, None, length=chain)
                    return s[-1]

                ck = jax.jit(chained).lower(P).compile()
                t, t1, tk, unrel = chain_min(lambda: single(P),
                                             lambda: ck(P), chain)
                flops = 2.0 * m * nb * nb  # useful flops (see module doc)
                emit({"metric": name, "seconds": round(t, 5),
                      "useful_gflops_rate": round(flops / t / 1e9, 1),
                      "chain_unreliable": unrel,
                      "seconds_single_dispatch": round(t1, 4)})
        except Exception as ex:
            emit({"metric": name, "ok": False,
                  "error": f"{type(ex).__name__}: {ex}"[:300]})

    panel_stage(12288, 512)
    panel_stage(12288, 256)
    panel_stage(4096, 256)

    # ---- 2. solve-side: multi-RHS + refine cost at 4096^2 ----
    from dhqr_tpu.ops.differentiable import lstsq_diff

    def lstsq_stage(n, k_rhs, refine, chain=5, watchdog=420):
        name = f"lstsq_{n}_k{k_rhs}_refine{refine}"
        _stage(name)
        try:
            with _Watchdog(name, watchdog):
                A = jnp.asarray(rng.random((n, n)), jnp.float32)
                B = jnp.asarray(rng.random((n, k_rhs)), jnp.float32) \
                    if k_rhs > 1 else jnp.asarray(rng.random(n), jnp.float32)
                sync(A)
                args = (256, "highest", True, False, "fast", "loop", refine)

                single = jax.jit(
                    lambda A, B: lstsq_diff(A, B, *args).ravel()[0]
                ).lower(A, B).compile()

                def chained(A, B):
                    def body(C, _):
                        x = lstsq_diff(C, B, *args)
                        keep = jnp.where(jnp.isfinite(x.ravel()[0]),
                                         jnp.float32(1.0), jnp.float32(0.0))
                        return C * keep, x.ravel()[0]
                    _, s = lax.scan(body, A, None, length=chain)
                    return s[-1]

                ck = jax.jit(chained).lower(A, B).compile()
                t, t1, tk, unrel = chain_min(lambda: single(A, B),
                                             lambda: ck(A, B), chain)
                emit({"metric": name, "seconds": round(t, 4),
                      "chain_unreliable": unrel, "k_rhs": k_rhs,
                      "refine": refine,
                      "seconds_single_dispatch": round(t1, 4)})
        except Exception as ex:
            emit({"metric": name, "ok": False,
                  "error": f"{type(ex).__name__}: {ex}"[:300]})

    lstsq_stage(4096, 1, 0)
    lstsq_stage(4096, 1, 1)
    lstsq_stage(4096, 64, 0)
    _stage("done")


if __name__ == "__main__":
    main()
