"""Round-3 TPU probe: the SHARDED engines on real TPU hardware.

The multichip proof so far is the driver's virtual-CPU dryrun
(`__graft_entry__.dryrun_multichip`) — it validates compilation and
collective correctness, but no shard_map program had ever executed on the
real chip. Only one chip is reachable through the tunnel, so this runs
every distributed engine on a ONE-device mesh: the degenerate case still
builds and executes the full distributed program — shard_map tracing,
psum-per-panel broadcast/reduce choreography, store-layout chaining, the
TSQR all-gather combine, the CholQR psum — on TPU hardware, against the
same `lstsq(mesh=...)` public surface a pod user calls.

Stages (each one JSONL line): column-sharded blocked lstsq in both
layouts (block + cyclic), row-sharded TSQR lstsq, row-sharded CholQR
lstsq, each at 2048x1792 f32 with a residual check against the
single-device engine answer.

Run ONE instance at a time (the axon relay allows a single TPU process).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main() -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    from bench import _Watchdog

    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    import dhqr_tpu
    from dhqr_tpu.parallel.mesh import column_mesh
    from dhqr_tpu.parallel.sharded_tsqr import row_mesh
    from dhqr_tpu.utils.profiling import sync

    _stage("backend_init")
    with _Watchdog("backend_init", 150):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    rng = np.random.default_rng(0)

    def emit(rec):
        rec["platform"] = platform
        rec["device_kind"] = kind
        print(json.dumps(rec), flush=True)

    m, n = 2048, 1792
    A = jnp.asarray(rng.random((m, n)), jnp.float32)
    b = jnp.asarray(rng.random(m), jnp.float32)
    sync(A)

    _stage("single_device_reference")
    with _Watchdog("single_device_reference", 300):
        x_ref = dhqr_tpu.lstsq(A, b, norm="fast")
        sync(x_ref)
        x_ref_h = np.asarray(x_ref)

    def stage(name, fn, watchdog=420):
        _stage(name)
        try:
            with _Watchdog(name, watchdog):
                t0 = time.perf_counter()
                x = fn()
                sync(x)
                total_s = time.perf_counter() - t0
                rel = float(np.linalg.norm(np.asarray(x) - x_ref_h) /
                            max(np.linalg.norm(x_ref_h), 1e-30))
                emit({"metric": name, "ok": True,
                      "seconds_total_first_call": round(total_s, 2),
                      "rel_diff_vs_single_device": rel,
                      "agrees": rel < 1e-3})
        except Exception as ex:
            emit({"metric": name, "ok": False,
                  "error": f"{type(ex).__name__}: {ex}"[:400]})

    cmesh = column_mesh(1)
    stage("sharded_lstsq_block_layout_tpu",
          lambda: dhqr_tpu.lstsq(A, b, mesh=cmesh, norm="fast"))
    stage("sharded_lstsq_cyclic_layout_tpu",
          lambda: dhqr_tpu.lstsq(A, b, mesh=cmesh, layout="cyclic",
                                 norm="fast"))
    rmesh = row_mesh(1)
    stage("sharded_tsqr_lstsq_tpu",
          lambda: dhqr_tpu.lstsq(A, b, mesh=rmesh, engine="tsqr"))
    stage("sharded_cholqr_lstsq_tpu",
          lambda: dhqr_tpu.lstsq(A, b, mesh=rmesh, engine="cholqr2"))
    _stage("done")


if __name__ == "__main__":
    main()
