"""Round-5 TPU probe: one-panel-lookahead schedule vs the default order.

Lookahead factors panel k+1 (and issues its psum, on the sharded tier)
BEFORE panel k's wide trailing GEMM (ops/blocked._scan_panels_lookahead).
On one chip there is no collective to hide, so the single-device ladder
here measures the pure reorder cost/benefit — XLA may still schedule the
independent panel/trailing programs differently (the round-3 phase probe
put the serial panel sweep at ~1/3 of total time at nb=512, the region
the reference's author flags "this is most expensive", reference
src/DistributedHouseholderQR.jl:141-143). Each stage emits a matched
PAIR (default, lookahead) at the same (n, nb, flat) so the delta is
read directly off adjacent rows.

Run ONE instance at a time (the axon relay allows a single TPU process).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main() -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    from bench import _Watchdog

    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from dhqr_tpu.ops.blocked import _apply_q_impl, _blocked_qr_impl
    from dhqr_tpu.ops.solve import r_matrix
    from dhqr_tpu.utils.profiling import sync

    _stage("backend_init")
    with _Watchdog("backend_init", 150):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    rng = np.random.default_rng(0)

    def emit(rec):
        rec["platform"] = platform
        rec["device_kind"] = kind
        print(json.dumps(rec), flush=True)

    def chain_time(n, nb, chain, watchdog, lookahead, repeats=3,
                   backward_error=False):
        name = f"qr_{n}_nb{nb}" + ("_lookahead" if lookahead else "_default")
        _stage(name)
        try:
            with _Watchdog(name, watchdog):
                A = jnp.asarray(rng.random((n, n)), jnp.float32)
                sync(A)
                kw = dict(precision="highest", pallas=True, norm="fast",
                          panel_impl="loop", lookahead=lookahead)
                t0 = time.perf_counter()
                single = _blocked_qr_impl.lower(A, nb, **kw).compile()
                H, al = single(A)
                sync(al)

                def chained(A):
                    def body(C, _):
                        Hc, ac = _blocked_qr_impl(C, nb, **kw)
                        return Hc, ac[0]
                    return lax.scan(body, A, None, length=chain)

                ck = jax.jit(chained).lower(A).compile()
                compile_s = time.perf_counter() - t0
                Hc, s = ck(A)
                sync(s)

                def tmin(f, pick):
                    ts = []
                    for _ in range(repeats):
                        t0 = time.perf_counter()
                        r = f(A)
                        sync(pick(r))
                        ts.append(time.perf_counter() - t0)
                    return min(ts)

                t1 = tmin(single, lambda r: r[1])
                tk = tmin(ck, lambda r: r[1])
                t = (tk - t1) / (chain - 1)
                unreliable = not (tk > t1 * 1.05 and t > 0)
                if unreliable:
                    t = t1
                flops = (4.0 / 3.0) * n**3
                rec = {"metric": f"qr_gflops_per_chip_f32_{n}x{n}",
                       "value": round(flops / t / 1e9, 2),
                       "unit": "GFLOP/s", "seconds": round(t, 4),
                       "block_size": nb, "lookahead": lookahead,
                       "chain_length": chain,
                       "seconds_single_dispatch": round(t1, 4),
                       "seconds_chain": round(tk, 4),
                       "compile_seconds": round(compile_s, 2),
                       "chain_unreliable": unreliable}
                if backward_error:
                    QR = _apply_q_impl(H, r_matrix(H, al), nb,
                                       precision="highest")
                    rec[f"backward_error_{n}"] = float(
                        jnp.linalg.norm(QR - A) / jnp.linalg.norm(A))
                emit(rec)
        except Exception as ex:
            emit({"metric": name, "ok": False,
                  "error": f"{type(ex).__name__}: {ex}"[:400]})

    # Matched pairs, smallest-first; accuracy evidence on the small size.
    # The default halves of the pairs double as fresh controls against the
    # round-3 numbers (same configs as tpu_r3_scale.jsonl).
    chain_time(1024, 256, 5, 240, False, backward_error=True)
    chain_time(1024, 256, 5, 240, True, backward_error=True)
    chain_time(4096, 256, 25, 560, False)
    chain_time(4096, 256, 25, 560, True)
    chain_time(8192, 256, 5, 560, False)
    chain_time(8192, 256, 5, 560, True)
    chain_time(12288, 512, 3, 580, False, repeats=2)
    chain_time(12288, 512, 3, 580, True, repeats=2)
    chain_time(16384, 512, 3, 580, False, repeats=2)
    chain_time(16384, 512, 3, 580, True, repeats=2)
    _stage("done")


if __name__ == "__main__":
    main()
