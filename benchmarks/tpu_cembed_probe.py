"""Round-4 TPU probe: complex64 lstsq via the real embedding, on hardware.

The axon relay has no complex support at MXU shapes (c64 work fails
UNIMPLEMENTED and poisons the compile helper — tpu_r3_disambig.jsonl), so
the reference's ComplexF64 capability was platform-blocked through round 3.
``dhqr_tpu.lstsq`` now routes complex64 through the exactly-equivalent real
embedded system (f32 end-to-end on the device; component extraction on the
host) — this probe runs that path on the real chip and checks the
reference's 8x normal-equations criterion against the host LAPACK oracle.

Entirely f32 on the device by construction; safe to run after any stage.
Emits one JSONL row per size. Single TPU process rule applies.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main() -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    from bench import _Watchdog

    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    import dhqr_tpu
    from dhqr_tpu.utils.profiling import sync
    from dhqr_tpu.utils.testing import (
        TOLERANCE_FACTOR,
        normal_equations_residual,
        oracle_residual,
    )

    _stage("backend_init")
    with _Watchdog("backend_init", 240):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    rng = np.random.default_rng(0)

    def stage(m, n, watchdog):
        name = f"c64_embed_lstsq_{m}x{n}"
        _stage(name)
        try:
            with _Watchdog(name, watchdog):
                A = ((rng.random((m, n)) - 0.5)
                     + 1j * (rng.random((m, n)) - 0.5)).astype(np.complex64)
                b = ((rng.random(m) - 0.5)
                     + 1j * (rng.random(m) - 0.5)).astype(np.complex64)
                t0 = time.perf_counter()
                x = dhqr_tpu.lstsq(A, b)  # embedding route on this backend
                sync(x)
                t_cold = time.perf_counter() - t0
                t0 = time.perf_counter()
                x = dhqr_tpu.lstsq(A, b)
                sync(x)
                t_warm = time.perf_counter() - t0
                xh = np.asarray(x)
                res = normal_equations_residual(A, xh, b)
                ref = oracle_residual(A, b)
                # complex flop model: 8 m n^2 real flops for complex QR;
                # the embedded system actually does 16 (2x) — report the
                # USEFUL (complex-problem) rate, embedding overhead priced
                # in, like the reference counts its own work.
                flops = 8.0 * m * n * n
                print(json.dumps({
                    "metric": f"c64_embed_lstsq_gflops_{m}x{n}",
                    "value": round(flops / t_warm / 1e9, 2),
                    "unit": "GFLOP/s (useful, embedding priced in)",
                    "seconds_warm": round(t_warm, 4),
                    "seconds_cold_incl_compile": round(t_cold, 2),
                    "normal_eq_residual": float(res),
                    "oracle_residual": float(ref),
                    "tolerance": float(TOLERANCE_FACTOR * ref),
                    "pass": bool(res < TOLERANCE_FACTOR * ref),
                    "platform": platform, "device_kind": kind,
                }), flush=True)
        except Exception as ex:
            print(json.dumps({"metric": name, "ok": False,
                              "error": f"{type(ex).__name__}: {ex}"[:400],
                              "platform": platform}), flush=True)

    stage(550, 500, 420)       # a reference-ladder-shaped case (m = 1.1n)
    stage(2048, 1024, 480)
    stage(4400, 4000, 560)     # the reference's endpoint shape, complex
    _stage("done")


if __name__ == "__main__":
    main()
