"""Round-3 TPU probe: TSQR with fused Pallas leaves on hardware.

The tall-skinny probe measured the XLA-leaf TSQR at 0.24-0.73 s per
65536 x 256 factorization (12-36 GFLOP/s-equivalent — the vmapped leaf
panel loops are latency/HBM-bound). This probe answers:

1. does the VMAPPED Pallas panel kernel lower under Mosaic (vmap adds a
   grid dimension — interpret-mode tests cannot catch a Mosaic rejection,
   same blind spot as round 3's unbatched lowering probe)?
2. how much does it recover? (leaves become in-VMEM kernels; trailing
   GEMMs unchanged)

Stages mirror tpu_tallskinny_probe.py exactly (same shapes, same chain
protocol, same dense-QR-equivalent flop model) so lines are directly
comparable.

Run ONE instance at a time (the axon relay allows a single TPU process).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main() -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    from bench import _Watchdog

    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from dhqr_tpu.ops.tsqr import _tsqr_lstsq_impl, _tsqr_r_impl
    from dhqr_tpu.utils.profiling import sync

    _stage("backend_init")
    with _Watchdog("backend_init", 150):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    rng = np.random.default_rng(0)

    def emit(rec):
        rec["platform"] = platform
        rec["device_kind"] = kind
        print(json.dumps(rec), flush=True)

    def qr_flops(m, n):
        return 2.0 * m * n * n - (2.0 / 3.0) * n**3

    # 1. Mosaic lowering of the vmapped kernel (the go/no-go datum).
    _stage("vmapped_lowering")
    try:
        with _Watchdog("vmapped_lowering", 240):
            from dhqr_tpu.ops.pallas_panel import _panel_qr_pallas_impl

            P = jnp.asarray(rng.standard_normal((8, 2048, 128)), jnp.float32)
            f = jax.jit(jax.vmap(
                lambda p: _panel_qr_pallas_impl(p, 0, interpret=False)))
            pf, al = f(P)
            sync(al)
            emit({"metric": "vmapped_pallas_lowering", "ok": True,
                  "finite": bool(jnp.all(jnp.isfinite(al)))})
    except Exception as ex:
        emit({"metric": "vmapped_pallas_lowering", "ok": False,
              "error": f"{type(ex).__name__}: {ex}"[:500]})
        _stage("done")  # no point measuring further
        return

    def tsqr_stage(m, n, nblk, chain, watchdog, repeats=3):
        name = f"tsqr_r_pallas_{m}x{n}_blocks{nblk}"
        _stage(name)
        try:
            with _Watchdog(name, watchdog):
                A = jnp.asarray(rng.random((m, n)), jnp.float32)
                sync(A)
                kw = dict(precision="highest", pallas=True, interpret=False)
                t0 = time.perf_counter()
                single = jax.jit(lambda A: _tsqr_r_impl(
                    A, nblk, 128, **kw)[0, 0]).lower(A).compile()
                s = single(A)
                sync(s)

                def chained(A):
                    def body(C, _):
                        R = _tsqr_r_impl(C, nblk, 128, **kw)
                        keep = jnp.where(jnp.isfinite(R[0, 0]),
                                         jnp.float32(1.0), jnp.float32(0.0))
                        return C * keep, R[0, 0]
                    _, ss = lax.scan(body, A, None, length=chain)
                    return ss[-1]

                ck = jax.jit(chained).lower(A).compile()
                compile_s = time.perf_counter() - t0
                s = ck(A)
                sync(s)

                def tmin(f):
                    ts = []
                    for _ in range(repeats):
                        t0 = time.perf_counter()
                        r = f(A)
                        sync(r)
                        ts.append(time.perf_counter() - t0)
                    return min(ts)

                t1, tk = tmin(single), tmin(ck)
                t = (tk - t1) / (chain - 1)
                unreliable = not (tk > t1 * 1.05 and t > 0)
                if unreliable:
                    t = t1
                emit({"metric": f"tsqr_r_pallas_f32_{m}x{n}_blocks{nblk}",
                      "value": round(qr_flops(m, n) / t / 1e9, 2),
                      "unit": "GFLOP/s",
                      "flop_model": "2mn^2-(2/3)n^3 (dense-QR-equivalent)",
                      "seconds": round(t, 5), "chain_length": chain,
                      "seconds_single_dispatch": round(t1, 4),
                      "seconds_chain": round(tk, 4),
                      "compile_seconds": round(compile_s, 2),
                      "chain_unreliable": unreliable,
                      "engine": "tsqr+pallas", "n_blocks": nblk})
        except Exception as ex:
            emit({"metric": name, "ok": False,
                  "error": f"{type(ex).__name__}: {ex}"[:500]})

    # Same shape/blocks as the XLA-leaf baseline lines for direct diffs.
    tsqr_stage(65536, 256, 8, 25, 420)
    tsqr_stage(65536, 256, 32, 25, 420)

    # lstsq at the BASELINE config-5 shape (XLA-leaf baseline: 1.55 s).
    _stage("tsqr_lstsq_pallas_131072x512")
    try:
        with _Watchdog("tsqr_lstsq_pallas_131072x512", 480):
            m2, n2 = 131072, 512
            A2 = jnp.asarray(rng.random((m2, n2)), jnp.float32)
            b2 = jnp.asarray(rng.random((m2,)), jnp.float32)
            sync(A2)
            kw = dict(precision="highest", pallas=True, interpret=False)
            t0 = time.perf_counter()
            single = jax.jit(lambda A, b: _tsqr_lstsq_impl(
                A, b, 16, 128, **kw)[0]).lower(A2, b2).compile()
            s = single(A2, b2)
            sync(s)
            compile_s = time.perf_counter() - t0
            ts = []
            for _ in range(3):
                t0 = time.perf_counter()
                s = single(A2, b2)
                sync(s)
                ts.append(time.perf_counter() - t0)
            t1 = min(ts)
            emit({"metric": f"tsqr_lstsq_pallas_f32_{m2}x{n2}",
                  "value": round((qr_flops(m2, n2) + 2.0 * m2 * n2)
                                 / t1 / 1e9, 2),
                  "unit": "GFLOP/s", "seconds_single_dispatch": round(t1, 4),
                  "compile_seconds": round(compile_s, 2),
                  "engine": "tsqr+pallas", "n_blocks": 16,
                  "config": "BASELINE-5 shape",
                  "note": "single-dispatch (RTT-bound if < ~0.1 s)"})
    except Exception as ex:
        emit({"metric": "tsqr_lstsq_pallas_131072x512", "ok": False,
              "error": f"{type(ex).__name__}: {ex}"[:500]})

    # ---- c64 diagnostics: the scale probe's c64 4096^2 stage failed with
    # a bare UNIMPLEMENTED; isolate which piece (planar Pallas kernel vs
    # the XLA complex path, e.g. complex triangular_solve) doesn't lower.
    from dhqr_tpu.ops.blocked import _blocked_qr_impl

    _stage("c64_pallas_panel")
    try:
        with _Watchdog("c64_pallas_panel", 240):
            from dhqr_tpu.ops.pallas_panel import _panel_qr_pallas_impl

            pc = jnp.asarray(rng.random((2048, 128)) +
                             1j * rng.random((2048, 128)), jnp.complex64)
            pf, al = _panel_qr_pallas_impl(pc, 0, interpret=False)
            sync(al)
            emit({"metric": "c64_pallas_panel_2048x128", "ok": True,
                  "finite": bool(jnp.all(jnp.isfinite(al)))})
    except Exception as ex:
        emit({"metric": "c64_pallas_panel_2048x128", "ok": False,
              "error": f"{type(ex).__name__}: {ex}"[:500]})

    _stage("c64_xla_blocked")
    try:
        with _Watchdog("c64_xla_blocked", 300):
            Ac = jnp.asarray(rng.random((1024, 1024)) +
                             1j * rng.random((1024, 1024)), jnp.complex64)
            sync(Ac)
            H, al = _blocked_qr_impl(Ac, 128, precision="highest",
                                     pallas=False, norm="fast")
            sync(al)
            emit({"metric": "c64_xla_blocked_1024", "ok": True,
                  "finite": bool(jnp.all(jnp.isfinite(al)))})
    except Exception as ex:
        emit({"metric": "c64_xla_blocked_1024", "ok": False,
              "error": f"{type(ex).__name__}: {ex}"[:500]})

    # ---- largest square that fits 32-bit buffer addressing (32768^2 f32
    # is exactly 2^32 bytes and failed; 24576^2 = 2.4 GB).
    _stage("qr_24576_nb512")
    try:
        with _Watchdog("qr_24576_nb512", 560):
            A3 = jnp.asarray(rng.random((24576, 24576)), jnp.float32)
            sync(A3)
            kw = dict(precision="highest", pallas=True, norm="fast",
                      panel_impl="loop")
            t0 = time.perf_counter()
            single = _blocked_qr_impl.lower(A3, 512, **kw).compile()
            H, al = single(A3)
            sync(al)
            compile_s = time.perf_counter() - t0
            ts = []
            for _ in range(2):
                t0 = time.perf_counter()
                H, al = single(A3)
                sync(al)
                ts.append(time.perf_counter() - t0)
            t1 = min(ts)
            n3 = 24576
            emit({"metric": f"qr_gflops_per_chip_f32_{n3}x{n3}",
                  "value": round((4.0 / 3.0) * n3**3 / t1 / 1e9, 2),
                  "unit": "GFLOP/s", "block_size": 512,
                  "pallas_panels": True, "seconds": round(t1, 4),
                  "compile_seconds": round(compile_s, 2),
                  "note": "single-dispatch; device time >> RTT"})
    except Exception as ex:
        emit({"metric": "qr_24576_nb512", "ok": False,
              "error": f"{type(ex).__name__}: {ex}"[:500]})
    _stage("done")


if __name__ == "__main__":
    main()
