"""dhqr-wire acceptance: compressed collectives across the sharded tier.

The round-18 decision artifact (benchmarks/README "Round-18 decision
rules"): every sharded engine family x CPU topology P in {2, 4, 8} x
comms wire format in {f32, bf16, int8},

1. **traced wire volume** — the dhqr-audit jaxpr census
   (``analysis.comms_pass.collect_comms``) per cell; the bf16 rows on
   the panel-broadcast engines (blocked/unblocked/solve) and the TSQR
   combine path must show >= 1.8x byte reduction vs their f32 twins
   (the same reduction DHQR302's compressed-mode budgets enforce
   statically in ``tools/lint.sh`` — this artifact is the committed
   evidence the gate replays);
2. **accuracy** — a real solve per cell, normal-equations residual
   within the reference 8x-LAPACK criterion: the column engines
   through the model tier (whose compressed path carries CSNE recovery
   by contract), the row engines through their in-body sweeps;
3. **bit identity** — the ``accurate`` preset's factorization is
   bitwise equal to the plain (pre-seam) spelling at every topology:
   ``comms=None`` is a verbatim passthrough by construction;
4. **zero warm recompiles** — each compressed mode compiles once;
   warm repeats count zero ``backend_compile`` events
   (``jax.monitoring``), per mode, per topology.

Ends with a ``serving_wire_verdict`` row the regress gate's ``wire-*``
rules enforce from then on.

Usage:  python benchmarks/serving_wire.py
Writes: benchmarks/results/serving_wire_<platform>.jsonl (append)
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8"
                               ).strip()

DEVICE_COUNTS = (2, 4, 8)
MODES = (None, "bf16", "int8")
#: Engines whose bf16 traced-volume ratio the verdict REQUIRES >= 1.8x
#: (the ISSUE-14 acceptance paths: panel broadcasts + the TSQR
#: combine). cholqr's Gram path is reported, not required — its
#: audit-scale CSNE sidecar makes the tiny-shape ratio ~1.79 while
#: real shapes sit at ~2x.
RATIO_REQUIRED = ("unblocked_qr", "blocked_qr", "sharded_solve",
                  "tsqr_lstsq")
RATIO_BAR = 1.8


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main() -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    rnd = int(os.environ.get("DHQR_ROUND", "18"))
    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import monitoring

    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(_REPO, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

    from bench import SCHEMA_VERSION, _Watchdog

    compiles = {"n": 0}
    monitoring.register_event_duration_secs_listener(
        lambda name, *a, **k: compiles.__setitem__("n", compiles["n"] + 1)
        if name == "/jax/core/compile/backend_compile_duration" else None)

    from dhqr_tpu.analysis.comms_pass import collect_comms
    from dhqr_tpu.models.qr_model import lstsq as model_lstsq
    from dhqr_tpu.parallel.mesh import column_mesh
    from dhqr_tpu.parallel.sharded_cholqr import sharded_cholqr_lstsq
    from dhqr_tpu.parallel.sharded_qr import (
        sharded_blocked_qr,
        sharded_householder_qr,
    )
    from dhqr_tpu.parallel.sharded_solve import sharded_lstsq, sharded_solve
    from dhqr_tpu.parallel.sharded_tsqr import row_mesh, sharded_tsqr_lstsq
    from dhqr_tpu.utils.profiling import sync
    from dhqr_tpu.utils.testing import (
        TOLERANCE_FACTOR,
        normal_equations_residual,
        oracle_residual,
    )

    _stage("backend_init")
    with _Watchdog("backend_init", 240):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    out_path = os.path.join(_REPO, "benchmarks", "results",
                            f"serving_wire_{platform}.jsonl")
    navail = len(jax.devices())
    counts = tuple(p for p in DEVICE_COUNTS if p <= navail)
    if not counts:
        # The dryrun-wire-stage convention: a 1-device backend has no
        # wire volume to compress — say so loudly instead of crashing
        # on the empty matrix below (XLA_FLAGS is read once at init,
        # so a pre-set flag string without the device-count flag lands
        # here).
        print("serving_wire: SKIPPED (needs >= 2 devices; set XLA_FLAGS="
              "--xla_force_host_platform_device_count=8 before the first "
              "backend touch)", file=sys.stderr, flush=True)
        return

    def emit(rec):
        rec.update(platform=platform, device_kind=kind, round=rnd,
                   schema_version=SCHEMA_VERSION)
        line = json.dumps(rec)
        print(line, flush=True)
        with open(out_path, "a") as f:
            f.write(line + "\n")

    rng = np.random.default_rng(0)

    def problems(P):
        """Per-topology shapes: column engines at n = 8P (every device
        holds real panels), row engines tall-skinny."""
        n, nb = 8 * P, 4
        m = 2 * n
        # nt = 32: at nt = 16 / P = 2 the tsqr CSNE sidecar (f32 by
        # design) eats the combine's bf16 ratio down to 1.79; at real
        # head sizes the sidecar is O(1/(P*n)) and the ratio sits at 2.
        mt, nt = 64 * P, 32
        cmesh, rmesh = column_mesh(P), row_mesh(P)
        A = jnp.asarray(rng.random((m, n)), jnp.float32)
        b = jnp.asarray(rng.random(m), jnp.float32)
        At = jnp.asarray(rng.random((mt, nt)), jnp.float32)
        bt = jnp.asarray(rng.random(mt), jnp.float32)
        H, alpha = jax.block_until_ready(
            sharded_blocked_qr(A, cmesh, block_size=nb))
        return dict(P=P, n=n, nb=nb, m=m, mt=mt, nt=nt, cmesh=cmesh,
                    rmesh=rmesh, A=A, b=b, At=At, bt=bt, H=H, alpha=alpha)

    def tracers(ctx):
        """(family, comms -> closed-jaxpr thunk) per engine family."""
        P, nb = ctx["P"], ctx["nb"]
        yield ("unblocked_qr", lambda c: jax.make_jaxpr(
            lambda A: sharded_householder_qr(A, ctx["cmesh"], comms=c)
        )(ctx["A"]))
        yield ("blocked_qr", lambda c: jax.make_jaxpr(
            lambda A: sharded_blocked_qr(A, ctx["cmesh"], block_size=nb,
                                         comms=c))(ctx["A"]))
        yield ("sharded_solve", lambda c: jax.make_jaxpr(
            lambda H, a, b: sharded_solve(H, a, b, ctx["cmesh"],
                                          block_size=nb, comms=c)
        )(ctx["H"], ctx["alpha"], ctx["b"]))
        yield ("tsqr_lstsq", lambda c: jax.make_jaxpr(
            lambda A, b: sharded_tsqr_lstsq(A, b, ctx["rmesh"],
                                            block_size=8, comms=c)
        )(ctx["At"], ctx["bt"]))
        yield ("cholqr_lstsq", lambda c: jax.make_jaxpr(
            lambda A, b: sharded_cholqr_lstsq(A, b, ctx["rmesh"], comms=c)
        )(ctx["At"], ctx["bt"]))

    def runners(ctx):
        """(family, comms -> x, residual problem (A, b)) per family.
        The column families solve through the tiers that carry the
        compressed-mode recovery contract."""
        nb = ctx["nb"]
        yield ("blocked_qr", lambda c: model_lstsq(
            ctx["A"], ctx["b"], mesh=ctx["cmesh"], block_size=nb, comms=c),
            (ctx["A"], ctx["b"]))
        yield ("sharded_solve", lambda c: sharded_lstsq(
            ctx["A"], ctx["b"], ctx["cmesh"], block_size=nb, comms=c)
            if c is None else model_lstsq(
                ctx["A"], ctx["b"], mesh=ctx["cmesh"], block_size=nb,
                comms=c),
            (ctx["A"], ctx["b"]))
        yield ("tsqr_lstsq", lambda c: sharded_tsqr_lstsq(
            ctx["At"], ctx["bt"], ctx["rmesh"], block_size=8, comms=c),
            (ctx["At"], ctx["bt"]))
        yield ("cholqr_lstsq", lambda c: sharded_cholqr_lstsq(
            ctx["At"], ctx["bt"], ctx["rmesh"], comms=c),
            (ctx["At"], ctx["bt"]))

    # ---- phase 1: traced wire volume ------------------------------------
    _stage("traced_volume")
    ratio_rows = []
    required_ok = True
    with _Watchdog("traced_volume", 1800):
        for P in counts:
            ctx = problems(P)
            for family, trace in tracers(ctx):
                vols = {}
                for comms in MODES:
                    stats = collect_comms(trace(comms))
                    vols[comms or "f32"] = stats.total_volume_bytes()
                for comms in ("bf16", "int8"):
                    ratio = vols["f32"] / max(vols[comms], 1)
                    req = comms == "bf16" and family in RATIO_REQUIRED
                    if req and ratio < RATIO_BAR:
                        required_ok = False
                    ratio_rows.append((family, P, comms, ratio))
                    emit({
                        "metric": "serving_wire_volume",
                        "engine": family, "devices": P, "comms": comms,
                        "value": round(ratio, 4),
                        "unit": "f32 traced bytes / compressed traced bytes",
                        "traced_bytes_f32": vols["f32"],
                        "traced_bytes_compressed": vols[comms],
                        "ratio_required": req,
                        "ratio_bar": RATIO_BAR if req else None,
                    })

    # ---- phase 2: accuracy across the matrix ----------------------------
    _stage("residuals")
    worst = 0.0
    cells = gated = 0
    with _Watchdog("residuals", 2400):
        for P in counts:
            ctx = problems(P)
            for family, run, (Aref, bref) in runners(ctx):
                ref = oracle_residual(np.asarray(Aref), np.asarray(bref))
                for comms in MODES:
                    x = run(comms)
                    res = normal_equations_residual(
                        Aref, np.asarray(x), bref)
                    ratio = res / ref if ref > 0 else float(res > 0)
                    cells += 1
                    gated += ratio < TOLERANCE_FACTOR
                    worst = max(worst, ratio)
                    emit({
                        "metric": "serving_wire_residual",
                        "engine": family, "devices": P,
                        "comms": comms or "f32",
                        "value": round(ratio, 4),
                        "unit": "normal-equations residual / LAPACK oracle",
                        "residual_criterion": TOLERANCE_FACTOR,
                        "within_8x": bool(ratio < TOLERANCE_FACTOR),
                    })

    # ---- phase 3: accurate is bit-identical -----------------------------
    _stage("bit_identity")
    bit_identical = True
    with _Watchdog("bit_identity", 1200):
        for P in counts:
            ctx = problems(P)
            H0, a0 = sharded_blocked_qr(ctx["A"], ctx["cmesh"],
                                        block_size=ctx["nb"])
            H1, a1 = sharded_blocked_qr(ctx["A"], ctx["cmesh"],
                                        block_size=ctx["nb"],
                                        policy="accurate")
            same = (np.array_equal(np.asarray(H0), np.asarray(H1))
                    and np.array_equal(np.asarray(a0), np.asarray(a1)))
            bit_identical = bit_identical and same
            emit({"metric": "serving_wire_bit_identity", "devices": P,
                  "accurate_equals_plain": bool(same)})

    # ---- phase 4: zero warm recompiles per compressed mode --------------
    _stage("warm_recompiles")
    warm_recompiles = 0
    with _Watchdog("warm_recompiles", 1200):
        for P in counts:
            ctx = problems(P)
            for comms in ("bf16", "int8"):
                # cold pass compiles; the counter window opens after it.
                sync(sharded_blocked_qr(ctx["A"], ctx["cmesh"],
                                        block_size=ctx["nb"], comms=comms))
                sync(sharded_tsqr_lstsq(ctx["At"], ctx["bt"], ctx["rmesh"],
                                        block_size=8, comms=comms))
                before = compiles["n"]
                sync(sharded_blocked_qr(ctx["A"], ctx["cmesh"],
                                        block_size=ctx["nb"], comms=comms))
                sync(sharded_tsqr_lstsq(ctx["At"], ctx["bt"], ctx["rmesh"],
                                        block_size=8, comms=comms))
                delta = compiles["n"] - before
                warm_recompiles += delta
                emit({"metric": "serving_wire_recompiles", "devices": P,
                      "comms": comms, "warm_recompiles": delta})

    # ---- phase 5: DHQR306 under the compressed wire model ---------------
    # Armed pulse over compressed dispatches: the traced census carries
    # the COMPRESSED avals, so the DHQR306 wire bound is automatically
    # the compressed bound; every report must verdict green (ok, or
    # skip-with-reason on CPU's unpublished interconnect) and carry the
    # wire_format tag (capture-once per w<mode> label).
    _stage("pulse_compressed")
    from dhqr_tpu.obs import pulse as pulse_mod

    pulse_rows = []
    dhqr306_ok = True
    with _Watchdog("pulse_compressed", 1200):
        # contexts built BEFORE arming: problems() warms a PLAIN
        # blocked dispatch, which an armed store would capture as an
        # untagged report.
        ctxs = [problems(P) for P in counts]
        with pulse_mod.pulsed() as store:
            for ctx in ctxs:
                for comms in ("bf16", "int8"):
                    sync(sharded_blocked_qr(ctx["A"], ctx["cmesh"],
                                            block_size=ctx["nb"],
                                            comms=comms))
                    sync(sharded_tsqr_lstsq(ctx["At"], ctx["bt"],
                                            ctx["rmesh"], block_size=8,
                                            comms=comms))
        for rep in store.reports():
            dhqr306_ok = dhqr306_ok and rep.dhqr306_pass
            pulse_rows.append(rep)
            emit({"metric": "serving_wire_pulse",
                  "dhqr306_pass": rep.dhqr306_pass,
                  "wire_format": rep.wire_format,
                  "pulse": rep.to_json()})
    wire_tagged = all(r.wire_format in ("bf16", "int8")
                      for r in pulse_rows)

    # ---- verdict --------------------------------------------------------
    min_required = min(r for f, _p, c, r in ratio_rows
                       if c == "bf16" and f in RATIO_REQUIRED)
    ok = (required_ok and gated == cells and bit_identical
          and warm_recompiles == 0 and dhqr306_ok and bool(pulse_rows)
          and wire_tagged)
    emit({
        "metric": "serving_wire_verdict",
        "kind": "verdict",
        "value": round(min_required, 4),
        "unit": "min bf16 traced-volume ratio over the required "
                "panel-broadcast/combine paths",
        "ratio_bar": RATIO_BAR,
        "volume_ratio_meets_bar": bool(required_ok),
        "residual_cells": cells,
        "residual_cells_within_8x": gated,
        "worst_residual_ratio": round(worst, 4),
        "accurate_bit_identical": bool(bit_identical),
        "warm_recompiles_compressed": warm_recompiles,
        "compressed_pulse_reports": len(pulse_rows),
        "dhqr306_all_green_compressed": bool(dhqr306_ok),
        "pulse_reports_wire_tagged": bool(wire_tagged),
        "topologies": list(counts),
        "ok": bool(ok),
    })
    _stage("done")


if __name__ == "__main__":
    main()
