"""Round-4 TPU probe: phase-attribute the 16384^2 headline (VERDICT r3 #4).

Two mechanisms, because the axon tunnel may not surface device-side trace
events:

1. ``utils/profiling.trace`` around one warm full-size dispatch — writes a
   perfetto/TensorBoard trace directory (committed when small enough; the
   engines' named scopes panel_factor / trailing_update / back_substitute
   = the reference's t1a/t1b/t2, src:126-146, 291-292).
2. A DIFFERENTIAL breakdown that needs no profiler: chain-time (a) the
   full QR and (b) the bare panel ladder — the fused Pallas kernel on
   exactly the (m - k*nb, nb) panel shapes the factorization visits,
   chained in one dispatch. panel_s = (b); trailing+other = (a) - (b).
   The trailing GEMM flops are known exactly, so the table reports the
   trailing update's achieved TF/s and what fraction of the wall is
   panel vs trailing vs other.

Emits JSONL rows; the final row is the breakdown table. Single TPU
process; smallest-first; 560-580 s watchdogs.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main() -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    from bench import _Watchdog

    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

    from dhqr_tpu.ops.blocked import _blocked_qr_impl
    from dhqr_tpu.ops.pallas_panel import _panel_qr_pallas_impl
    from dhqr_tpu.utils.profiling import sync, trace

    _stage("backend_init")
    with _Watchdog("backend_init", 240):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    rng = np.random.default_rng(0)

    N = int(os.environ.get("DHQR_PHASE_N", "16384"))
    NB = int(os.environ.get("DHQR_PHASE_NB", "512"))
    CHAIN = 3
    REPEATS = 2

    def emit(rec):
        rec["platform"] = platform
        rec["device_kind"] = kind
        print(json.dumps(rec), flush=True)

    kw = dict(precision="highest", pallas=True, norm="fast",
              panel_impl="loop")

    def tmin(f, A, pick, repeats=REPEATS):
        ts = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            r = f(A)
            sync(pick(r))
            ts.append(time.perf_counter() - t0)
        return min(ts)

    # --- stage 1: full QR, single + chain (the headline protocol) -------
    _stage(f"full_qr_{N}")
    A = jnp.asarray(rng.random((N, N)), jnp.float32)
    sync(A)
    full_t = None
    try:
        with _Watchdog("full_qr", 580):
            single = _blocked_qr_impl.lower(A, NB, **kw).compile()
            H, al = single(A)
            sync(al)

            def chained(A):
                def body(C, _):
                    Hc, ac = _blocked_qr_impl(C, NB, **kw)
                    return Hc, ac[0]
                return lax.scan(body, A, None, length=CHAIN)

            ck = jax.jit(chained).lower(A).compile()
            _, s = ck(A)
            sync(s)
            t1 = tmin(single, A, lambda r: r[1])
            tk = tmin(ck, A, lambda r: r[1])
            full_t = (tk - t1) / (CHAIN - 1)
            if not (tk > t1 * 1.05 and full_t > 0):
                full_t = t1
            flops = (4.0 / 3.0) * N**3
            emit({"metric": f"full_qr_{N}_nb{NB}", "seconds": round(full_t, 4),
                  "gflops": round(flops / full_t / 1e9, 2),
                  "seconds_single": round(t1, 4), "seconds_chain": round(tk, 4)})
    except Exception as ex:
        emit({"metric": "full_qr", "ok": False,
              "error": f"{type(ex).__name__}: {ex}"[:400]})
        return

    # --- stage 2: bare panel ladder, one dispatch ----------------------
    # The factorization visits panels of height N - q*NB, width NB; the
    # fused kernel factors each in VMEM. A scan over the TALLEST shape with
    # masked rows would change the work; instead chain the exact ladder as
    # one jitted program of dependent kernel calls (output feeds a cheap
    # scalar into the next input so XLA cannot elide stages).
    _stage("panel_ladder")
    try:
        with _Watchdog("panel_ladder", 580):
            heights = [N - q * NB for q in range(N // NB)]

            def ladder(A):
                acc = jnp.float32(0.0)
                outs = []
                for h in heights:
                    panel = lax.dynamic_slice(A, (0, 0), (h, NB)) + acc
                    pf, a_k = _panel_qr_pallas_impl(panel, 0, interpret=False)
                    acc = a_k[0] * jnp.float32(1e-30)  # data dependence only
                    outs.append(a_k[0])
                return jnp.stack(outs).sum() + acc

            lj = jax.jit(ladder).lower(A).compile()
            s = lj(A)
            sync(s)
            panel_t = tmin(lj, A, lambda r: r)
            emit({"metric": f"panel_ladder_{N}_nb{NB}",
                  "seconds": round(panel_t, 4),
                  "panels": len(heights)})
    except Exception as ex:
        emit({"metric": "panel_ladder", "ok": False,
              "error": f"{type(ex).__name__}: {ex}"[:400]})
        panel_t = None

    # --- stage 3: perfetto trace of one warm dispatch -------------------
    _stage("profiler_trace")
    trace_dir = os.path.join(_REPO, "benchmarks", "results",
                             f"trace_qr{N}_nb{NB}")
    trace_ok = False
    try:
        with _Watchdog("profiler_trace", 300):
            with trace(trace_dir):
                H, al = single(A)
                sync(al)
            trace_ok = True
    except Exception as ex:
        emit({"metric": "profiler_trace", "ok": False,
              "error": f"{type(ex).__name__}: {ex}"[:400]})

    # --- breakdown table -------------------------------------------------
    if panel_t is not None and full_t:
        other_t = max(full_t - panel_t, 0.0)
        # Trailing-update GEMM flops: sum over panels of
        # 4 * (m-k) * nb * (n-k-nb) (compact-WY: two applies' worth counted
        # by the standard 2mnk per GEMM x the W/Y pair) — approximate with
        # the classical attribution total_flops - panel_flops.
        panel_flops = sum(2.0 * h * NB * NB - (2.0 / 3.0) * NB**3
                          for h in [N - q * NB for q in range(N // NB)])
        total_flops = (4.0 / 3.0) * N**3
        trailing_flops = total_flops - panel_flops
        emit({
            "metric": f"phase_breakdown_{N}_nb{NB}",
            "full_seconds": round(full_t, 4),
            "panel_seconds": round(panel_t, 4),
            "trailing_plus_other_seconds": round(other_t, 4),
            "panel_fraction": round(panel_t / full_t, 3),
            "panel_gflops": round(panel_flops / max(panel_t, 1e-9) / 1e9, 1),
            "trailing_gflops_upper_bound": round(
                trailing_flops / max(other_t, 1e-9) / 1e9, 1),
            "trace_dir": trace_dir if trace_ok else None,
        })
    _stage("done")


if __name__ == "__main__":
    main()
