"""Precision-policy A/B ladder: trailing precision x refine, any backend.

The decision table for the round-6 tentpole (VERDICT r5 item 2 — "the
obvious 2-3x lever"): for every trailing-GEMM precision in the ladder
(highest / high / default), factor once at the error-anchor size and
measure

* the FACTOR backward error ||QR - A||_F / ||A||_F (refine-independent:
  it is a property of the factorization itself);
* the SOLVE backward error eta(x) = ||A x - b|| / (||A||_F ||x|| + ||b||)
  at refine = 0 and refine = 1, REUSING the factorization — the pair that
  shows one refinement sweep buying a cheap factor's error back;
* wall seconds per factorization (chain-timed on TPU where the tunnel RTT
  would otherwise dominate; direct elsewhere).

Emits one JSONL row per trailing precision (stdout + the results file).
On CPU the MXU pass count collapses to native f32, so the CPU artifact
pins the PLUMBING and the refinement mechanics (errors must sit at f32
roundoff for every cell, <= 1e-5 after refine=1 per the acceptance bar);
the TPU run of the same script (or bench.py's ladder stages, which share
the stage configs) decides the adopted default.

Usage:  python benchmarks/policy_ladder.py [n]     (default n=1024)
Writes: benchmarks/results/policy_ladder_<platform>.jsonl (append).
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _stage(name: str) -> None:
    print(f"::stage {name} t={time.time():.1f}", file=sys.stderr, flush=True)


def main(n: int = 1024) -> None:
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(3))
    from bench import SCHEMA_VERSION, ROUND, _Watchdog, _chained_qr

    _stage("import")
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(_REPO, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

    from dhqr_tpu.ops.blocked import (_apply_q_impl, _apply_qt_impl,
                                      _blocked_qr_impl)
    from dhqr_tpu.ops.solve import back_substitute, r_matrix
    from dhqr_tpu.precision import TRAILING_PRECISIONS
    from dhqr_tpu.utils.profiling import sync
    from dhqr_tpu.utils.testing import solve_backward_error

    _stage("backend_init")
    with _Watchdog("backend_init", 240):
        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", "?")
        sync(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    _stage(f"backend_ready_{platform}")
    on_tpu = platform == "tpu"
    nb = 256 if on_tpu else 128
    chain = 5 if on_tpu else 0
    out_path = os.path.join(_REPO, "benchmarks", "results",
                            f"policy_ladder_{platform}.jsonl")

    rng = np.random.default_rng(0)
    A = jnp.asarray(rng.random((n, n)), jnp.float32)
    b = jnp.asarray(rng.random((n,)), jnp.float32)
    sync(A)

    def emit(rec):
        rec.update(platform=platform, device_kind=kind, round=ROUND,
                   schema_version=SCHEMA_VERSION)
        line = json.dumps(rec)
        print(line, flush=True)
        with open(out_path, "a") as f:
            f.write(line + "\n")

    def cell(tprec):
        name = f"policy_{n}_tp-{tprec}"
        _stage(name)
        split = None if tprec == "highest" else tprec
        kw = dict(precision="highest", pallas=on_tpu, norm="fast",
                  panel_impl="loop", trailing_precision=split)
        with _Watchdog(name, 560 if on_tpu else 240):
            t0 = time.perf_counter()
            single = _blocked_qr_impl.lower(A, nb, **kw).compile()
            compile_s = time.perf_counter() - t0
            H, al = single(A)
            sync(al)
            ts = []
            for _ in range(3):
                t0 = time.perf_counter()
                H, al = single(A)
                sync(al)
                ts.append(time.perf_counter() - t0)
            t = t1 = min(ts)
            unreliable = False
            if chain:
                # Chain-timed on TPU: the tunnel RTT is present once in
                # both measurements and cancels in the delta (bench.py's
                # protocol, same shared program builder).
                ck = jax.jit(_chained_qr(_blocked_qr_impl, lax, nb, kw,
                                         chain)).lower(A).compile()
                _, sc = ck(A)
                sync(sc)
                ts = []
                for _ in range(3):
                    t0 = time.perf_counter()
                    _, sc = ck(A)
                    sync(sc)
                    ts.append(time.perf_counter() - t0)
                tk = min(ts)
                delta = (tk - t1) / (chain - 1)
                if tk > t1 * 1.05 and delta > 0:
                    t = delta
                else:
                    unreliable = True
            # Factor backward error (refine cannot change it).
            QR = _apply_q_impl(H, r_matrix(H, al), nb, precision="highest")
            ferr = float(jnp.linalg.norm(QR - A) / jnp.linalg.norm(A))

            # Solve backward error at refine 0/1, reusing (H, al).
            def qr_solve(rhs):
                return back_substitute(
                    H, al, _apply_qt_impl(H, rhs, nb, precision="highest"))

            def eta(xv):
                return solve_backward_error(A, xv, b)

            x0 = qr_solve(b)
            r_ = b - jnp.matmul(A, x0, precision="highest")
            x1 = x0 + qr_solve(r_)
            flops = (4.0 / 3.0) * n**3
            rec = {
                "metric": f"qr_policy_ladder_{n}x{n}",
                "trailing_precision": tprec,
                "value": round(flops / t / 1e9, 2), "unit": "GFLOP/s",
                "seconds": round(t, 4), "block_size": nb,
                "precision": "highest",
                "compile_seconds": round(compile_s, 2),
                f"backward_error_{n}": ferr,
                "solve_backward_error_refine0": eta(x0),
                "solve_backward_error_refine1": eta(x1),
                "error_target": 1e-5,
                "pallas_panels": on_tpu,
            }
            if chain:
                rec["chain_length"] = chain
                if unreliable:
                    rec["chain_unreliable"] = True
            emit(rec)
            return rec

    rows = [cell(t) for t in TRAILING_PRECISIONS]
    _stage("done")
    # One-line verdict for the session log: does every cell meet the
    # acceptance bar (<= 1e-5 solve backward error after one refinement)?
    ok = all(r["solve_backward_error_refine1"] <= 1e-5 for r in rows)
    print(json.dumps({"metric": "policy_ladder_verdict", "n": n,
                      "all_cells_refine1_below_1e-5": ok,
                      "platform": platform, "round": ROUND}), flush=True)


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 1024)
