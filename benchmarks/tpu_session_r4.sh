#!/bin/bash
# Round-4 hardware session: run the must-have headline FIRST, then the
# perf experiments, strictly sequentially (ONE TPU process at a time).
# Safe to re-run; every stage appends to its own durable artifact.
#
#   bash benchmarks/tpu_session_r4.sh [stage...]
#
# Stages (default: all, in this order — the order IS the protocol:
# headline before risky probes, VERDICT r3 #1):
# Artifact names carry the round tag R = r${DHQR_ROUND:-5} (bench.py and
# the analyzer honor the same variable, same default):
#   alive     - relay health check (exits nonzero if wedged; later stages skip)
#   bench     - full bench.py supervised run (headline into bench_${R}_run.jsonl
#               + per-stage tee into bench_tpu_tee.jsonl)
#   split     - split-panel ladder      -> tpu_${R}_split.jsonl
#   lookahead - lookahead-vs-default pairs -> tpu_${R}_lookahead.jsonl
#   agg       - aggregated-trailing-update ladder -> tpu_${R}_agg.jsonl
#   reconstruct - reconstruction-panel ladder -> tpu_${R}_reconstruct.jsonl
#   trailing  - trailing-precision pairs -> tpu_${R}_trailing.jsonl
#   phase     - 16384^2 phase breakdown -> tpu_${R}_phase16k.jsonl
#   cembed    - c64 lstsq via real embedding -> tpu_${R}_cembed.jsonl
#   bigsize   - 24576/28672 capacity incl. donating engine -> tpu_${R}_bigsize.jsonl
set -u
cd "$(dirname "$0")/.."
RES=benchmarks/results
# Artifact round tag; default matches bench.py/analyze_r4.py, and the 'r'
# prefix is stripped if present so DHQR_ROUND=r5 and =5 agree with their
# lenient parse (an unstripped 'r5' would write tpu_rr5_* artifacts the
# analyzer never globs).
_rnd="${DHQR_ROUND:-5}"; _rnd="${_rnd#r}"; _rnd="${_rnd#R}"
R="r${_rnd}"
mkdir -p "$RES"
STAGES=${*:-"alive bench agg reconstruct split lookahead trailing phase cembed bigsize"}

# Validate every stage name BEFORE running anything: a typo in a later
# argument must not abort the session after earlier multi-hundred-second
# stages already spent the hardware window.
for s in $STAGES; do
  case "$s" in
    alive|bench|agg|reconstruct|split|lookahead|trailing|phase|cembed|bigsize) ;;
    *) echo "unknown stage '$s' (valid: alive bench agg reconstruct split" \
            "lookahead trailing phase cembed bigsize)" >&2
       exit 1 ;;
  esac
done

run() { # name, logfile, cmd...
  local name=$1 log=$2; shift 2
  echo "=== $name: $* (log: $log)" >&2
  "$@" 2>>"$log.stderr" | tee -a "$log"
  local rc=${PIPESTATUS[0]}
  echo "=== $name done rc=$rc" >&2
  return "$rc"
}

# Probe stages keep their Python-level SIGTERM handlers (graceful claim
# release when NOT wedged), but a PJRT wedge can GIL-starve every internal
# watchdog (see tpu_alive_probe.py's CAVEAT) — so each probe also gets an
# outer kernel-level bound. 3600 s is far above any healthy probe's total
# runtime; on a wedge it caps the loss at one hour of the hardware window
# instead of all of it. The bench stage runs under the same bound: its
# widened TPU window (1500 s) + SIGTERM grace + CPU fallback tops out
# ~1650 s, comfortably inside, and the supervisor's own child escalation
# handles everything short of a GIL-starved supervisor.
probe() { # name, logfile, cmd...
  local name=$1 log=$2; shift 2
  run "$name" "$log" timeout -k 30 3600 "$@"
}

for s in $STAGES; do
  case "$s" in
    alive)
      # Outer kernel-level kill: the probe's internal watchdogs can be
      # GIL-starved when PJRT init blocks in C++ (see the probe's CAVEAT)
      # — without this, a wedged relay hangs the whole session here.
      run alive "$RES/tpu_${R}_alive.log" \
        timeout -k 30 900 python benchmarks/tpu_alive_probe.py || exit 2 ;;
    bench)
      # .jsonl, not .json: the stage tees bench.py's multi-line stdout and
      # re-runs APPEND — the artifact is a line stream, never one JSON
      # document (ADVICE r4). The TPU child's window is widened beyond the
      # driver-sized 470 s default: THIS session owns its wall clock, and
      # the full escalation (incl. the round-5 lookahead/agg stages, cold
      # compiles) needs the room; the probe() 3600 s outer bound and the
      # child's per-stage watchdogs still cap a wedge.
      # Watchdog scale 3: a stage that would fire mid-compile wedges the
      # relay for every later session (measured 08:36 this round — the
      # 240 s qr_4096 watchdog vs ~2x-slower-than-r3 cold compiles); in a
      # session that owns its wall clock, minutes of a hung stage are the
      # cheaper failure. The child window widens to match; probe()'s
      # 3600 s outer bound still caps a truly wedged run.
      # SKIP_BANKED: stages that already produced a round-tagged TPU row
      # (in the tee) re-emit it instead of re-compiling — a short
      # recovery window jumps straight to the unbanked headline sizes.
      # Outer bound 4500 (not probe()'s 3600): the widened TPU child
      # window (2800) + CPU fallback can legitimately reach ~3400 s, and
      # the outer kill is the one bound that can land as SIGKILL
      # mid-claim — it must only fire on a truly hung supervisor.
      # The TPU child budget honors an inherited DHQR_BENCH_TPU_TIMEOUT:
      # a watcher that recovers close to its deadline shrinks it so the
      # bench cannot overrun into the driver's round-end window (a
      # two-process TPU collision can wedge the relay for both).
      # Round-6: pre-warm the persistent compile cache in a throwaway
      # child before any stage watchdog arms (DHQR_BENCH_PREWARM_TIMEOUT;
      # the prewarm child self-budgets and never dies mid-compile), so
      # the armed escalation meets only warm compiles — the round-5
      # mid-compile-watchdog wedge cannot recur. Its budget rides INSIDE
      # the widened window: the outer bound grows by the same amount.
      _bt="${DHQR_BENCH_TPU_TIMEOUT:-2800}"
      _pw="${DHQR_BENCH_PREWARM_TIMEOUT:-900}"
      run bench "$RES/bench_${R}_run.jsonl" \
        timeout -k 30 $(( _bt + _pw + 1700 )) \
        env DHQR_BENCH_TPU_TIMEOUT="$_bt" DHQR_BENCH_WATCHDOG_SCALE=3 \
            DHQR_BENCH_SKIP_BANKED=1 DHQR_BENCH_PREWARM_TIMEOUT="$_pw" \
        python bench.py ;;
    agg)
      probe agg "$RES/tpu_${R}_agg.jsonl" \
        python benchmarks/tpu_agg_probe.py ;;
    reconstruct)
      probe reconstruct "$RES/tpu_${R}_reconstruct.jsonl" \
        python benchmarks/tpu_reconstruct_probe.py ;;
    split)
      probe split "$RES/tpu_${R}_split.jsonl" \
        python benchmarks/tpu_split_probe.py ;;
    lookahead)
      probe lookahead "$RES/tpu_${R}_lookahead.jsonl" \
        python benchmarks/tpu_lookahead_probe.py ;;
    trailing)
      probe trailing "$RES/tpu_${R}_trailing.jsonl" \
        python benchmarks/tpu_trailing_precision_probe.py ;;
    phase)
      probe phase "$RES/tpu_${R}_phase16k.jsonl" \
        python benchmarks/tpu_phase16k_probe.py ;;
    cembed)
      probe cembed "$RES/tpu_${R}_cembed.jsonl" \
        python benchmarks/tpu_cembed_probe.py ;;
    bigsize)
      probe bigsize "$RES/tpu_${R}_bigsize.jsonl" \
        python benchmarks/tpu_bigsize_probe.py ;;
    *) echo "unknown stage $s" >&2; exit 1 ;;
  esac
done

# Durable decision table the moment the session ends — the analysis must
# not depend on someone remembering to run it before the round closes.
python benchmarks/analyze_r4.py > "$RES/analysis_${R}.txt" 2>&1 || true
echo "=== analysis written to $RES/analysis_${R}.txt" >&2
