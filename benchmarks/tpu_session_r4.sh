#!/bin/bash
# Round-4 hardware session: run the must-have headline FIRST, then the
# perf experiments, strictly sequentially (ONE TPU process at a time).
# Safe to re-run; every stage appends to its own durable artifact.
#
#   bash benchmarks/tpu_session_r4.sh [stage...]
#
# Stages (default: all, in this order — the order IS the protocol:
# headline before risky probes, VERDICT r3 #1):
# Artifact names carry the round tag R = r${DHQR_ROUND:-4} (the analyzer
# honors the same variable):
#   alive     - relay health check (exits nonzero if wedged; later stages skip)
#   bench     - full bench.py supervised run (headline into bench_${R}_run.json
#               + per-stage tee into bench_tpu_tee.jsonl)
#   split     - split-panel ladder      -> tpu_${R}_split.jsonl
#   trailing  - trailing-precision pairs -> tpu_${R}_trailing.jsonl
#   phase     - 16384^2 phase breakdown -> tpu_${R}_phase16k.jsonl
#   cembed    - c64 lstsq via real embedding -> tpu_${R}_cembed.jsonl
set -u
cd "$(dirname "$0")/.."
RES=benchmarks/results
R="r${DHQR_ROUND:-4}"   # artifact round tag: DHQR_ROUND=5 reuses this session in round 5
mkdir -p "$RES"
STAGES=${*:-"alive bench split trailing phase cembed"}

# Validate every stage name BEFORE running anything: a typo in a later
# argument must not abort the session after earlier multi-hundred-second
# stages already spent the hardware window.
for s in $STAGES; do
  case "$s" in
    alive|bench|split|trailing|phase|cembed) ;;
    *) echo "unknown stage '$s' (valid: alive bench split trailing phase" \
            "cembed)" >&2
       exit 1 ;;
  esac
done

run() { # name, logfile, cmd...
  local name=$1 log=$2; shift 2
  echo "=== $name: $* (log: $log)" >&2
  "$@" 2>>"$log.stderr" | tee -a "$log"
  local rc=${PIPESTATUS[0]}
  echo "=== $name done rc=$rc" >&2
  return "$rc"
}

for s in $STAGES; do
  case "$s" in
    alive)
      run alive "$RES/tpu_${R}_alive.log" \
        python benchmarks/tpu_alive_probe.py || exit 2 ;;
    bench)
      run bench "$RES/bench_${R}_run.json" python bench.py ;;
    split)
      run split "$RES/tpu_${R}_split.jsonl" \
        python benchmarks/tpu_split_probe.py ;;
    trailing)
      run trailing "$RES/tpu_${R}_trailing.jsonl" \
        python benchmarks/tpu_trailing_precision_probe.py ;;
    phase)
      run phase "$RES/tpu_${R}_phase16k.jsonl" \
        python benchmarks/tpu_phase16k_probe.py ;;
    cembed)
      run cembed "$RES/tpu_${R}_cembed.jsonl" \
        python benchmarks/tpu_cembed_probe.py ;;
    *) echo "unknown stage $s" >&2; exit 1 ;;
  esac
done
